"""Posterior sampling driver (reference ``R/sampleMcmc.R:68-380``).

TPU execution model (SURVEY.md §2.3 "Parallelism"):

- one jitted sweep per model config, ``lax.scan`` over iterations with
  strided sample recording (transient / thin handled inside the scan);
- independent chains are a leading batch axis via ``vmap``;
- multi-device: the chain axis (and optionally the species axis) is laid out
  over a ``jax.sharding.Mesh`` — XLA inserts the (trivial, gather-only)
  collectives; there is no inter-chain communication during sampling.

The reference's SOCK-cluster process fan-out collapses into this one
compiled program.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from ..model import Hmsc
from ..precompute import compute_data_parameters
from .structs import (DEFAULT_NF_CAP, build_model_data, build_spec, build_state)
from .sweep import effective_spec_data, make_sweep, record_sample
from . import spatial
from . import updaters as U

__all__ = ["sample_mcmc", "instrumented_sweep", "grow_carry_state"]


class _InlineWriter:
    """Synchronous stand-in for :class:`_SegmentWriter` (``pipeline=False``):
    every submitted item runs immediately on the caller's thread, restoring
    the pre-pipeline serialised behaviour for A/B and bit-identity tests."""

    def __init__(self):
        self.max_depth_seen = 0
        self.busy_s = 0.0

    def submit(self, fn):
        import time
        t0 = time.perf_counter()
        fn()
        self.busy_s += time.perf_counter() - t0

    def barrier(self):
        pass

    def shutdown(self):
        pass


class _SegmentWriter:
    """Background collector/writer for the pipelined sampling loop.

    One FIFO worker thread consumes submitted callables in order: segment
    fetches (``np.asarray`` of the packed device buffer — the device→host
    copy) and checkpoint serialisation + atomic rename both run here, off
    the segment loop's critical path, overlapping the next segment's device
    compute.  The queue is *bounded* (``depth``): when a slow disk or link
    falls behind, ``submit`` blocks — explicit backpressure, so pending
    host buffers can never grow without bound.

    An exception inside any item is captured and re-raised on the driver
    thread at the next ``submit``/``barrier`` (FIFO order is preserved:
    items submitted after a failure are skipped until the error is
    delivered).  ``barrier`` drains all in-flight work — the durability
    point before :class:`~hmsc_tpu.utils.checkpoint.PreemptedRun` unwinds
    and before the run returns."""

    # the captured failure crosses threads (set by the worker, swapped out
    # by the driver); `hmsc_tpu lint` enforces the declaration below
    # hmsc: guarded-by[_err_lock]: _err

    def __init__(self, depth: int = 2):
        import queue
        import threading
        if depth < 1:
            raise ValueError(f"pipeline_depth must be >= 1, got {depth}")
        self._q = queue.Queue(maxsize=int(depth))
        self._err = None
        self._err_lock = threading.Lock()
        self.max_depth_seen = 0
        self.busy_s = 0.0
        self._thread = threading.Thread(
            target=self._run, name="hmsc-segment-writer", daemon=True)
        self._thread.start()

    def _run(self):
        import time
        while True:
            item = self._q.get()
            try:
                if item is None:
                    return
                with self._err_lock:       # skip work after a failure
                    failed = self._err is not None
                if not failed:
                    t0 = time.perf_counter()
                    item()
                    self.busy_s += time.perf_counter() - t0
            except BaseException as e:     # noqa: BLE001 — delivered to driver
                with self._err_lock:
                    self._err = e
            finally:
                self._q.task_done()

    def _check(self):
        with self._err_lock:
            err, self._err = self._err, None
        if err is not None:
            raise err

    def submit(self, fn):
        self._check()
        self._q.put(fn)                    # blocks when full: backpressure
        # at least the just-submitted item was in flight; qsize() may
        # already read 0 when the worker drains instantly
        self.max_depth_seen = max(self.max_depth_seen, self._q.qsize(), 1)
        self._check()

    def barrier(self):
        """Wait for every submitted item to finish; raise any captured
        failure.  The fsync inside ``_atomic_savez`` has completed for all
        checkpoint items once this returns."""
        self._q.join()
        self._check()

    def shutdown(self):
        """Drain remaining items (best effort — later failures are
        swallowed; call ``barrier`` first when errors must propagate) and
        join the worker.  Safe to call twice."""
        if self._thread is None:
            return
        self._q.put(None)
        self._thread.join()
        self._thread = None


@functools.lru_cache(maxsize=16)
def _packer(n_leaves, cast=None):
    """Jitted raveled-concat: one contiguous device buffer per fetch."""
    def pack(*xs):
        flat = [x.ravel() for x in xs]
        if cast is not None:
            flat = [x.astype(cast) for x in flat]
        return jnp.concatenate(flat)
    return jax.jit(pack)


def _pack_records(recs, record_dtype=None):
    """Pack the f32 leaves of a recorded-sample pytree into ONE device buffer.

    A per-leaf ``np.asarray`` pays the device round-trip latency once per
    parameter (9+ round-trips); on a remote-attached TPU that latency is
    ~65 ms each and dominates the benchmark wall-clock.  The packed buffer
    makes the host copy one latency + pure bandwidth, and — dispatched
    asynchronously per segment — overlaps the copy with the next segment's
    compute."""
    leaves, treedef = jax.tree.flatten(recs)
    f32 = [i for i, l in enumerate(leaves)
           if l.dtype == jnp.float32 and l.size > 0]
    if len(f32) == 1 and record_dtype is not None:
        # single-leaf records skip packing but must still quantise
        i = f32[0]
        leaves[i] = jax.jit(lambda x: x.astype(record_dtype))(leaves[i])
    if len(f32) > 1:
        packed = _packer(len(f32), record_dtype)(*[leaves[i] for i in f32])
        # retain only shapes for the packed leaves — holding the original
        # device arrays until fetch time would double record HBM
        shapes = {i: leaves[i].shape for i in f32}
        for i in f32:
            leaves[i] = None
    else:
        packed, shapes = None, {}
    return packed, leaves, shapes, treedef, f32


def _unpack_records(packed, leaves, shapes, treedef, f32):
    """Host-side counterpart of :func:`_pack_records` (forces the fetch)."""
    out = list(leaves)
    if packed is not None:
        host = np.asarray(packed)
        if host.dtype != np.float32:          # record_dtype quantisation
            host = host.astype(np.float32)
        off = 0
        for i in f32:
            shape = shapes[i]
            n = int(np.prod(shape))
            # copy: a view would pin the whole packed buffer in host memory
            # for as long as any single parameter array is kept alive
            out[i] = host[off:off + n].reshape(shape).copy()
            off += n
    for i in range(len(out)):
        if not isinstance(out[i], np.ndarray):
            out[i] = np.asarray(out[i])
        # single-leaf record_dtype path: widen any narrow float (bf16, f16)
        # back to f32; leave f64-mode records untouched
        dt = out[i].dtype
        if jnp.issubdtype(dt, jnp.floating) and dt.itemsize < 4:
            out[i] = out[i].astype(np.float32)
    return jax.tree.unflatten(treedef, out)


# species-dimension index per array field (before any leading chain axis);
# fields not listed are replicated over the species mesh axis
_SPECIES_DIMS = {
    "Z": 1, "Beta": 1, "iSigma": 0, "Lambda": 1, "Psi": 1,
    "Y": 1, "Ymask": 1, "Tr": 0, "distr_family": 0,
    "distr_estsig": 0, "sigma_fixed": 0, "aSigma": 0, "bSigma": 0,
}

# guard against silent drift: every key must name a real struct field
from .structs import GibbsState as _GS, LevelState as _LS, ModelData as _MD  # noqa: E402
_known = {f.name for cls in (_GS, _LS, _MD)
          for f in __import__("dataclasses").fields(cls)}
_stale = set(_SPECIES_DIMS) - _known
assert not _stale, f"_SPECIES_DIMS names unknown struct fields: {_stale}"
del _GS, _LS, _MD, _known, _stale


def _shard_species(tree, mesh, spec, sp_axis, lead=None):
    """Place a (state or data) pytree on the mesh: optional leading chain
    axis, species dims from ``_SPECIES_DIMS`` on ``sp_axis``, everything
    else replicated."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    # device_put requires even shards; the caller gates divisibility
    sp_ok = sp_axis is not None

    def put(path, leaf):
        if not hasattr(leaf, "ndim") or leaf.ndim == 0:
            return leaf
        name = None
        for p in reversed(path):
            n = getattr(p, "name", None)
            if n is not None:
                name = n
                break
        ax = [None] * leaf.ndim
        off = 0
        if lead is not None:
            ax[0] = lead
            off = 1
        d = _SPECIES_DIMS.get(name) if sp_ok else None
        if d is not None and d + off < leaf.ndim \
                and leaf.shape[d + off] == spec.ns:
            ax[d + off] = sp_axis
        return jax.device_put(leaf, NamedSharding(mesh, P(*ax)))

    return jax.tree_util.tree_map_with_path(put, tree)


# names accepted by sample_mcmc(record=...); per-level variants ("Eta_0")
# are also accepted
_RECORDABLE = {"Beta", "Gamma", "V", "sigma", "rho", "Eta", "Lambda", "Psi",
               "Delta", "Alpha", "wRRR", "PsiRRR", "DeltaRRR"}


def _keep_record(name: str, record) -> bool:
    """Whether a recorded-sample key survives the ``record=`` selection.
    Beta and the per-level nfMask bookkeeping are always kept (posterior
    windowing and ragged-nf trimming need them)."""
    if record is None or name == "Beta" or name.startswith("nfMask"):
        return True
    head, _, tail = name.rpartition("_")
    base = head if tail.isdigit() else name
    return name in record or base in record


def normalize_record(spec, record):
    """Validate a ``record=`` restriction against ``spec`` and return it as
    a sorted hashable tuple (``None`` passes through).  Shared by
    ``sample_mcmc`` and the batched multitenant path — both feed the result
    into an ``lru_cache``'d runner, so the tuple form is load-bearing, and
    both owe the user the same rejection of names the model never emits."""
    if record is None:
        return None
    if isinstance(record, str):
        record = (record,)
    level_pars = {"Eta", "Lambda", "Psi", "Delta", "Alpha"}
    # names the model structure never emits: accepting them would pass
    # validation yet record nothing, and the user's later post[...] lookup
    # would blame the record= restriction instead of the model itself
    absent = set()
    if not spec.has_phylo:
        absent.add("rho")
    if spec.nc_rrr == 0:
        absent.update({"wRRR", "PsiRRR", "DeltaRRR"})
    if spec.nr == 0:
        absent.update(level_pars)
    bad, structural = [], []
    for k in record:
        head, _, tail = k.rpartition("_")
        if tail.isdigit():
            # suffixed names: only per-level parameters carry a level
            # index, and it must name an existing level — anything else
            # would pass validation yet silently record nothing
            if head not in level_pars or int(tail) >= spec.nr:
                bad.append(k)
        elif k in absent:
            structural.append(k)
        elif k not in _RECORDABLE:
            bad.append(k)
    if structural:
        raise ValueError(
            f"record: parameter(s) {structural} do not exist on this "
            "model ('rho' needs a phylogeny (C=/phylo_tree=); "
            "'wRRR'/'PsiRRR'/'DeltaRRR' need XRRRData; per-level "
            "parameters need at least one random level) — the run "
            "would silently record nothing for them")
    if bad:
        raise ValueError(
            f"record: unknown parameter name(s) {bad}; valid names are "
            f"{sorted(_RECORDABLE)} (per-level parameters "
            f"{sorted(level_pars)} also accept a _<level> suffix "
            f"below nr={spec.nr})")
    rec_set = set(record)
    # sign-alignment coupling: Eta flips with Lambda's sign, and Beta's
    # RRR rows flip with wRRR's — recording one without its sign
    # reference would leave it silently sign-mixed across chains, so the
    # reference array is force-included (both are small blocks)
    for k in list(rec_set):
        head, _, tail = k.rpartition("_")
        if k == "Eta" or (tail.isdigit() and head == "Eta"):
            rec_set.add("Lambda" if k == "Eta" else f"Lambda_{tail}")
    if spec.nc_rrr > 0:
        rec_set.add("wRRR")
    return tuple(sorted(rec_set))


def grow_carry_state(state, hM_old: Hmsc, hM_new: Hmsc, *, seed: int = 0,
                     nf_cap: int = DEFAULT_NF_CAP):
    """Warm-start entry for data-shape growth (streaming refits): re-shape a
    committed chain carry ``state`` (chains-leading, as checkpoints store
    it) fitted on ``hM_old`` so it initialises chains on ``hM_new`` — the
    same model with appended sampling rows (``ny`` grows) and possibly new
    random-level units (``np`` grows).

    All stream-defining parameter blocks (Beta, Gamma, iV, iSigma, Lambda,
    Psi, Delta, rho, BetaSel, the sweep counter ``it``) carry over
    untouched — only the data-shaped leaves change:

    - per-level ``Eta`` rows are scattered into the new unit order (the
      ``Hmsc`` constructor re-sorts unit labels, so an appended unit may
      land anywhere in the index space), and genuinely NEW units draw their
      rows from the N(0,1) factor prior (masked to the active factors),
      seeded deterministically by ``seed``;
    - ``Z``'s appended rows initialise at the carried state's linear
      predictor (exactly :func:`~hmsc_tpu.mcmc.structs.build_state`'s
      fresh-chain idiom — the in-sweep Z update re-draws them from the
      truncated/observed law on the first sweep).

    Structure changes that WOULD change the stream (ns/nc/nt/levels, factor
    caps, spatial methods) are rejected — a refit pins them from the parent
    run's metadata.  New units on spatial or covariate-dependent levels
    need per-unit data this entry cannot invent, and are rejected too."""
    spec_old = build_spec(hM_old, nf_cap)
    spec_new = build_spec(hM_new, nf_cap)
    fixed = ("ns", "nc", "nt", "nr", "ncsel", "nc_rrr", "has_phylo")
    diff = [f for f in fixed
            if getattr(spec_old, f) != getattr(spec_new, f)]
    if diff:
        raise ValueError(
            f"grow_carry_state: model structure changed in {diff} — a "
            "warm start can only grow the data axes (ny, per-level np); "
            "everything else is pinned from the parent run")
    if spec_new.ny < spec_old.ny:
        raise ValueError(
            f"grow_carry_state: ny shrank ({spec_old.ny} -> {spec_new.ny})"
            " — appends only")
    if spec_old.x_is_list or spec_new.x_is_list:
        raise NotImplementedError(
            "grow_carry_state: species-specific designs (X lists) are not "
            "refittable yet")
    n_chains = int(np.asarray(state.Z).shape[0])
    rng = np.random.default_rng(seed)

    new_levels = []
    etas_new = []
    for r in range(spec_new.nr):
        lo, ln = spec_old.levels[r], spec_new.levels[r]
        if (lo.nf_max, lo.nf_min, lo.ncr, lo.x_dim, lo.spatial) != \
                (ln.nf_max, ln.nf_min, ln.ncr, ln.x_dim, ln.spatial):
            raise ValueError(
                f"grow_carry_state: level {hM_new.rl_names[r]!r} changed "
                "structurally (factor bounds / unit covariates / spatial "
                "method) — pinned from the parent run")
        pos = {u: i for i, u in enumerate(hM_new.pi_names[r])}
        missing = [u for u in hM_old.pi_names[r] if u not in pos]
        if missing:
            raise ValueError(
                f"grow_carry_state: level {hM_new.rl_names[r]!r} lost "
                f"units {missing[:5]} — appends only")
        perm = np.array([pos[u] for u in hM_old.pi_names[r]],
                        dtype=np.int64)
        fresh = sorted(set(range(ln.n_units)) - set(perm.tolist()))
        eta_old = np.asarray(state.levels[r].Eta)
        eta = np.zeros((n_chains, ln.n_units, eta_old.shape[2]),
                       dtype=eta_old.dtype)
        eta[:, perm] = eta_old
        if fresh:
            if ln.spatial is not None:
                raise NotImplementedError(
                    f"grow_carry_state: new units on the spatial level "
                    f"{hM_new.rl_names[r]!r} need coordinates/grids the "
                    "warm start cannot invent — refit with rows at "
                    "existing units, or fit the grown level fresh")
            if ln.x_dim > 0:
                raise NotImplementedError(
                    f"grow_carry_state: new units on the covariate-"
                    f"dependent level {hM_new.rl_names[r]!r} (xDim > 0) "
                    "need per-unit covariates — not refittable yet")
            draw = rng.standard_normal(
                (n_chains, len(fresh), eta_old.shape[2]))
            mask = np.asarray(state.levels[r].nf_mask)      # (chains, nf)
            eta[:, fresh] = (draw * mask[:, None, :]).astype(eta_old.dtype)
        etas_new.append(eta)
        new_levels.append(state.levels[r].replace(Eta=jnp.asarray(eta)))

    Z_old = np.asarray(state.Z)
    m = spec_new.ny - spec_old.ny
    if m == 0:
        return state.replace(levels=tuple(new_levels))
    # appended rows start at the carried linear predictor, per chain
    Xs_new = np.asarray(hM_new.XScaled)[spec_old.ny:]
    Beta = np.asarray(state.Beta)                      # (chains, nc, ns)
    L = np.einsum("mk,cks->cms", Xs_new, Beta)
    for r in range(spec_new.nr):
        pi = hM_new.Pi[spec_old.ny:, r]
        lam = np.asarray(state.levels[r].Lambda)       # (chains, nf, ns, ncr)
        mask = np.asarray(state.levels[r].nf_mask)     # (chains, nf)
        lam = lam * mask[:, :, None, None]
        rL = hM_new.ranLevels[r]
        x_row = (rL.x_for(hM_new.pi_names[r])[pi] if rL.x_dim > 0
                 else np.ones((m, 1)))
        L = L + np.einsum("cmf,mk,cfsk->cms", etas_new[r][:, pi], x_row,
                          lam)
    Z = np.concatenate([Z_old, L.astype(Z_old.dtype)], axis=1)
    return state.replace(Z=jnp.asarray(Z), levels=tuple(new_levels))


@functools.lru_cache(maxsize=64)
def _compiled_runner(spec, updater_items, adapt_nf, samples, transient, thin,
                     skip_init_z, record=None, nngp_dense_max=None,
                     mesh=None, chain_axis="chains", species_axis="species",
                     precision=None, local_rng=False, site_axis="sites"):
    """One jitted chain-vmapped sampling program per static config.

    Keyed on the hashable (spec, updater toggles, scan lengths) so repeated
    ``sample_mcmc`` calls with the same shapes reuse the compiled executable
    (XLA compilation is the dominant cost for small models).
    ``nngp_dense_max`` carries the current NNGP dense/CG crossover into the
    key: the sweep reads it at trace time from the ``spatial`` module
    global, so an A/B that mutates it must not be handed the stale cached
    program.

    The carry arguments (state, keys, divergence tracker — argnums 1..3) are
    **donated**: each output carry aliases its input buffer, so the segment
    loop updates the chain state in place instead of holding two copies of
    the carry pytree in HBM per step.  Callers must treat the carry they
    passed in as consumed (``sample_mcmc`` copies caller-provided
    ``init_state``/``init_keys`` before the first donated call, and
    snapshots the carry on-device before a checkpoint boundary).  A
    ``samples=0`` config is a pure burn-in segment: the sample scan has
    length 0 and the recorded tree comes back empty along the sample axis.

    ``precision`` (a hashable :class:`~hmsc_tpu.mcmc.precision.
    PrecisionPolicy`) engages the mixed-precision sweep: the runner takes
    a trailing ``staged`` argument — the policy's bf16 shadow table
    (:func:`~hmsc_tpu.mcmc.precision.stage_data`), deliberately NOT
    donated (it is reused across every segment, unlike the carry) — and
    the policy'd blocks trace inside their compute scopes.
    ``precision=None`` keeps the historical 4-argument runner,
    trace-identical to every prior release.  ``local_rng`` switches the
    sharded sweep's species-dim draws to shard-local streams (see
    :class:`~hmsc_tpu.mcmc.partition.ShardCtx`).

    ``mesh`` with a ``species_axis`` (and optionally a ``site_axis``)
    engages the SHARDED runner: the whole chain-vmapped program is
    wrapped in ``shard_map`` over the mesh with the in/out
    PartitionSpecs from :mod:`~hmsc_tpu.mcmc.partition`, each Gibbs
    block runs on its local species columns (and, on a 2D mesh, its
    local row/unit blocks — Z and Eta rows shard over sites) with
    explicit collectives at the cross-species and cross-site reductions,
    and the donated carry stays sharded (per-device state ~1/shards per
    engaged axis).  ``mesh=None`` (or a chains-only mesh) is the
    historical replicated program, trace-identical to every prior
    release (the committed fingerprints pin it)."""
    updater = dict(updater_items) if updater_items else None
    shard = None
    spec_run = spec
    n_st = 1
    if mesh is not None and species_axis in getattr(mesh, "axis_names", ()):
        import dataclasses as _dc

        from .partition import ShardCtx
        n_sp = int(mesh.shape[species_axis])
        if site_axis in getattr(mesh, "axis_names", ()):
            n_st = int(mesh.shape[site_axis])
        if n_sp > 1 or n_st > 1:
            if spec.ns % n_sp:
                raise ValueError(
                    f"ns={spec.ns} is not divisible by the mesh's "
                    f"'{species_axis}' extent ({n_sp}); the sampler should "
                    "have fallen back to replication")
            if n_st > 1 and (spec.ny % n_st
                             or any(ls.n_units % n_st
                                    for ls in spec.levels)):
                raise ValueError(
                    f"ny={spec.ny} / a level's unit count is not divisible "
                    f"by the mesh's '{site_axis}' extent ({n_st}); the "
                    "sampler should have fallen back to site replication")
            shard = ShardCtx(axis=species_axis, n=n_sp, ns=spec.ns,
                             local_rng=bool(local_rng),
                             site_axis=site_axis if n_st > 1 else None,
                             m=n_st if n_st > 1 else 1,
                             ny=spec.ny if n_st > 1 else 0,
                             np_r=tuple(ls.n_units for ls in spec.levels)
                             if n_st > 1 else ())
            spec_run = _dc.replace(spec, ns=spec.ns // n_sp,
                                   ny=spec.ny // n_st)
    sweep = make_sweep(spec_run, updater, adapt_nf, shard, precision)

    def first_bad_update(state, bad_it):
        """Track the first iteration whose carry went non-finite (divergence
        observability: the reference at best prints "Fail in Poisson Z update",
        updateZ.R:84-86; here every chain reports its first bad sweep).
        Sharded: the finiteness verdict is itself a cross-species
        reduction — a NaN on any shard must mark the chain on every
        shard, or the replicated bookkeeping would fork."""
        ok = jnp.bool_(True)
        for leaf in jax.tree.leaves(state):
            if jnp.issubdtype(leaf.dtype, jnp.floating):
                ok = ok & jnp.all(jnp.isfinite(leaf))
        if shard is not None:
            ok = shard.all_ok(ok)
        return jnp.where((bad_it < 0) & ~ok, state.it, bad_it)

    def run_chain(data, state, key, bad_it, staged=None):
        if not skip_init_z:
            # reference inits Z via one updateZ pass; a resumed or
            # continuation segment keeps its carried Z (and, so that the
            # stream is independent of host-side segmentation, no split)
            key, k0 = jax.random.split(key)
            spec0, data0 = effective_spec_data(spec_run, data, state)
            state = U.update_z(spec0, data0, state, k0, shard=shard)
        bad_it = first_bad_update(state, bad_it)

        def one_iter(carry, _):
            state, key, bad_it = carry
            key, sub = jax.random.split(key)
            if precision is None:
                state = sweep(data, state, sub)
            else:
                # same single consumption — only one branch ever traces
                # (static on `precision`), the policy'd sweep just takes
                # the staged table   # hmsc: ignore[rng-key-reuse]
                state = sweep(data, state, sub, staged)
            bad_it = first_bad_update(state, bad_it)
            return (state, key, bad_it), None

        carry = (state, key, bad_it)
        if transient > 0:
            carry, _ = jax.lax.scan(one_iter, carry, None, length=transient)

        def sample_step(carry, _):
            carry, _ = jax.lax.scan(one_iter, carry, None, length=thin)
            rec = record_sample(spec_run, data, carry[0])
            if record is not None:
                rec = {k: v for k, v in rec.items()
                       if _keep_record(k, record)}
            return carry, rec

        carry, recs = jax.lax.scan(sample_step, carry, None, length=samples)
        return recs, carry[0], carry[2], carry[1]

    if precision is None:
        mapped = jax.vmap(run_chain, in_axes=(None, 0, 0, 0))
    else:
        # the staged shadow table rides unbatched (shared by every
        # chain) and undonated (reused by every segment)
        mapped = jax.vmap(run_chain, in_axes=(None, 0, 0, 0, None))
    if shard is None:
        return jax.jit(mapped, donate_argnums=(1, 2, 3))

    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from .partition import (DATA_SITE_DIMS, DATA_SPECIES_DIMS,
                            STATE_SITE_DIMS, STATE_SPECIES_DIMS,
                            record_pspecs, tree_pspecs)
    st = site_axis if n_st > 1 else None
    rec_spec_for = record_pspecs(chain_axis, species_axis, site_axis=st)

    def fn(data, states, keys, bad, *staged_args):
        in_specs = (
            tree_pspecs(data, spec, species_axis, DATA_SPECIES_DIMS,
                        x_is_list=spec.x_is_list, site_axis=st,
                        site_dims=DATA_SITE_DIMS if st else None),
            tree_pspecs(states, spec, species_axis, STATE_SPECIES_DIMS,
                        lead=chain_axis, site_axis=st,
                        site_dims=STATE_SITE_DIMS if st else None),
            P(chain_axis), P(chain_axis))
        if precision is not None:
            from .precision import staged_pspecs
            in_specs = in_specs + (
                staged_pspecs(staged_args[0] or {}, spec, species_axis,
                              x_is_list=spec.x_is_list, site_axis=st),)
        state_out = in_specs[1]

        # the recorded-sample tree's structure is known statically from
        # record_sample + the record= filter (abstract eval on the GLOBAL
        # spec — shard_map out_specs need the tree's keys and ranks
        # before the body traces; +2 ranks for the (chain, sample) axes
        # the vmap/scan add)
        one_state = jax.tree.map(lambda x: x[0], states)
        rec_shapes = jax.eval_shape(
            lambda d, s: {k: v
                          for k, v in record_sample(spec, d, s).items()
                          if record is None or _keep_record(k, record)},
            data, one_state)
        rec_specs = {name: rec_spec_for(name, len(sd.shape) + 2)
                     for name, sd in rec_shapes.items()}
        out_specs = (rec_specs, state_out, P(chain_axis), P(chain_axis))
        return shard_map(mapped, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)(
                             data, states, keys, bad, *staged_args)

    return jax.jit(fn, donate_argnums=(1, 2, 3))


# timed repetitions per block in the instrumented (per-updater) sweep; the
# minimum over reps is reported, so dispatch jitter shrinks with more reps
_PROFILE_REPS = 3

# collective timeout while a coordinated divergence repair is in flight:
# healthy ranks wait out the owning rank's warm re-sample (up to a whole
# sampling window), so the per-commit timeout — sized for barrier skew,
# seconds — would falsely declare the fleet dead mid-repair
_RETRY_REPAIR_TIMEOUT_S = 3600.0


@functools.lru_cache(maxsize=8)
def _instrumented_steps(spec, updater_items, adapt_nf, vmapped):
    """Per-block jitted dispatchers for one Gibbs sweep — the NON-fused
    runner variant behind ``sample_mcmc(profile_updaters=...)`` and
    ``python -m hmsc_tpu profile --measured``.  Each schedule block
    (:func:`~hmsc_tpu.mcmc.sweep.make_sweep_schedule`) compiles as its own
    program so its wall time is observable with ``block_until_ready``; the
    production fused runner (:func:`_compiled_runner`) never uses these."""
    from .sweep import make_sweep_schedule
    updater = dict(updater_items) if updater_items else None
    steps = make_sweep_schedule(spec, updater, adapt_nf)
    out = []
    for name, block in steps:
        fn = (jax.vmap(block, in_axes=(None, 0, 0)) if vmapped else block)
        out.append((name, jax.jit(fn)))
    return tuple(out)


def instrumented_sweep(spec, data, state, key, updater: dict | None = None,
                       adapt_nf=None, vmapped: bool = False,
                       reps: int = _PROFILE_REPS, time_fused: bool = True):
    """Run ONE Gibbs sweep with every schedule block dispatched as its own
    jitted call, timing each with ``block_until_ready`` over ``reps``
    repetitions (minimum reported).  Returns ``(state_out, profile)``.

    The block sequence, subkey derivation and op order inside each block
    are identical to the fused sweep (both fold the same
    ``make_sweep_schedule``), so ``state_out`` is **bit-identical** to one
    fused sweep pass — ``tests/test_profile.py`` pins this per canonical
    spec.  The per-block timed calls re-run a block on the same inputs and
    discard the result, so timing never perturbs the state either.

    ``profile`` carries ``updaters`` (per-block ``wall_s``/``mean_s`` and
    ``share`` of the per-block total), ``updater_wall_s``, and — with
    ``time_fused`` — ``fused_wall_s`` plus ``attributed_frac`` = named
    updater wall over fused wall (bookkeeping steps, named in parentheses,
    are excluded from the numerator)."""
    import time

    from .sweep import make_sweep, sweep_prologue

    adapt_nf = tuple(int(a) for a in (adapt_nf
                                      or tuple(0 for _ in range(spec.nr))))
    updater_items = tuple(sorted(updater.items())) if updater else None
    steps = _instrumented_steps(spec, updater_items, adapt_nf, bool(vmapped))
    prologue = jax.jit(jax.vmap(sweep_prologue) if vmapped
                       else sweep_prologue)

    def _timed(fn, *args):
        out = jax.block_until_ready(fn(*args))   # compile + the real result
        times = []
        for _ in range(max(1, int(reps))):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args))
            times.append(time.perf_counter() - t0)
        return out, min(times), sum(times) / len(times)

    state_it, ks = prologue(state, key)
    carry = (state_it, None, None, None)
    blocks, named_total, total = [], 0.0, 0.0
    for name, jfn in steps:
        carry, wall, mean = _timed(jfn, data, carry, ks)
        blocks.append({"name": name, "wall_s": wall, "mean_s": mean})
        total += wall
        if not name.startswith("("):
            named_total += wall
    for b in blocks:
        b["share"] = round(b["wall_s"] / total, 4) if total > 0 else 0.0
        b["wall_s"] = round(b["wall_s"], 7)
        b["mean_s"] = round(b["mean_s"], 7)
    prof = {"reps": int(reps), "vmapped": bool(vmapped),
            "updaters": blocks, "updater_wall_s": round(total, 7)}
    if time_fused:
        sweep = make_sweep(spec, dict(updater_items) if updater_items
                           else None, adapt_nf)
        ffn = jax.jit(jax.vmap(sweep, in_axes=(None, 0, 0)) if vmapped
                      else sweep)
        _, fwall, _ = _timed(ffn, data, state, key)
        prof["fused_wall_s"] = round(fwall, 7)
        prof["attributed_frac"] = round(named_total / max(fwall, 1e-12), 4)
    return carry[0], prof


def _find_warm_restart(ck_dir, hM, bad, base_samples, samples):
    """Newest manifest in this run's snapshot directory at which every
    chain in ``bad`` was still healthy.  Returns (full carry state at that
    snapshot, local recorded samples at it, absolute transient_done for
    burn-in snapshots) or None when no such snapshot survives rotation —
    the caller then falls back to the cold from-scratch restart.

    Only snapshots inside this call's own sampling window qualify
    (``0 <= samples_at_snapshot - base_samples < samples``): a fresh run
    owns its directory and a resumed run continues it, so everything in
    that window is this logical run's history; a snapshot that predates the
    continuation cannot be spliced here (its draws live in the base
    segment), and the final post-divergence snapshot is excluded by the
    health check.  The manifest is loaded with ``mmap=True``: only the
    O(state) carry is read — the lazily-assembled posterior view is never
    touched, so probing candidates costs nothing even for long histories."""
    from ..utils import checkpoint as ckm

    for p in ckm.checkpoint_files(ck_dir):
        if not p.endswith(".json"):
            continue
        try:
            man = ckm.load_manifest(p)
        except ckm.CheckpointError:
            continue
        s0 = int(man.get("samples", 0)) - int(base_samples)
        if s0 < 0 or s0 >= int(samples):
            continue               # outside this call's sampling window
        fb = man.get("first_bad_it")
        if fb is None or any(int(fb[int(c)]) >= 0 for c in bad):
            continue               # some retried chain was already poisoned
        try:
            ck = ckm.load_manifest_checkpoint(p, hM, mmap=True)
        except ckm.CheckpointError:
            continue
        t_done = int(man.get("run", {}).get("transient_done", 0))
        return ck.state, s0, t_done
    return None


def _sweep_stale_events(dirpath) -> None:
    """Remove every ``events-p<r>.jsonl`` under ``dirpath``.  A fresh run
    owns its directory; a previous run's streams — possibly from more
    ranks than this run has, each rank only ever truncates its own — would
    make ``report`` merge dead ranks into the new run."""
    import os

    from ..obs.events import EVENTS_FILE_RE
    try:
        names = os.listdir(dirpath)
    except OSError:
        return
    for fn in names:
        if EVENTS_FILE_RE.fullmatch(fn):
            try:
                os.unlink(os.path.join(dirpath, fn))
            except OSError:
                pass


def sample_mcmc(hM: Hmsc, samples: int, transient: int = 0, thin: int = 1,
                n_chains: int = 1, seed: int | None = None, init_par=None,
                adapt_nf=None, updater: dict | None = None,
                nf_cap: int = DEFAULT_NF_CAP, dtype=jnp.float32,
                data_par=None, from_prior: bool = False,
                align_post: bool = True, mesh=None, chain_axis: str = "chains",
                species_axis: str = "species", site_axis: str = "sites",
                shard_sweep=None,
                return_state: bool = False, verbose: int = 0,
                init_state=None, profile_dir: str | None = None,
                rng_impl: str | None = None, record_dtype=None,
                retry_diverged: int = 0, record=None,
                precision_policy=None, local_rng: bool = False,
                checkpoint_every: int = 0, checkpoint_path: str | None = None,
                checkpoint_keep: int = 3,
                checkpoint_max_age_s: float | None = None,
                checkpoint_archive_every: int = 0,
                checkpoint_max_bytes: int | None = None,
                checkpoint_layout: str = "append",
                pipeline: bool = True, pipeline_depth: int = 2,
                init_keys=None, coordinator=None,
                telemetry=None, profile_segments=None,
                profile_updaters=None,
                progress_callback=None, _ckpt_base=None,
                _transient_base: int = 0, _ckpt_shards=None):
    """Run the blocked Gibbs sampler; returns a :class:`~hmsc_tpu.post.Posterior`.

    Arguments mirror the reference's ``sampleMcmc`` (samples/transient/thin/
    nChains/initPar/adaptNf/updater/dataParList/fromPrior/alignPost/verbose);
    the process-parallel ``nParallel`` is replaced by device parallelism via
    ``mesh``.  Extras over the reference:

    - ``verbose=N`` prints progress every N sweeps from inside the compiled
      scan (device callback).
    - ``init_state`` resumes chains from a saved carry state (see
      ``hmsc_tpu.utils.checkpoint``); transient should usually be 0 then.
    - ``profile_dir`` wraps the run in a ``jax.profiler`` trace.
    - the returned Posterior carries ``timing`` = {setup_s, run_s} wall-clock
      seconds (run_s includes compilation on first use of a config).
    - ``rng_impl`` picks the PRNG bit generator; default is the hardware
      ``rbg`` on TPU backends (the probit Z update is RNG-throughput-bound
      at scale) and ``threefry2x32`` elsewhere.  Reproducibility is bitwise
      per (seed, impl, package version) — not across impls, and not across
      releases (the sweep's internal key-splitting layout may change when
      updaters are added, which re-derives every subkey).
    - ``retry_diverged=N`` re-runs any chain whose carry went non-finite
      (fresh initial state and key stream, same config, burn-in covering the
      original chain's progress, up to N attempts) and splices the
      replacement into the returned posterior; the default 0 keeps the
      exclude-and-warn containment only.
    - ``updater={"Interweave": False}`` disables the beyond-reference
      per-factor (Eta, Lambda) scale interweaving (on by default; targets
      the identical posterior — see ``updaters.interweave_scale``).
      ``updater={"InterweaveLocation": False}`` disables the
      (Eta, Beta_intercept) location move (also on by default: exact,
      Geweke-validated, measured +10% min / +20% median Beta ESS at
      config-2 scale — see ``updaters.interweave_location``; it silently
      skips models where its invariance breaks, ``location_gate``).
      ``updater={"InterweaveDA": True}`` enables the ASIS flip of the
      probit data augmentation on the intercept row (redraw the intercept
      with the residual Z - Beta_int held fixed under the per-species sign
      intervals — see ``updaters.interweave_da_intercept``).
    - ``nf_cap`` bounds the per-level latent factor count (static XLA
      shapes; the reference instead grows nf up to ns).  Pick it a little
      above the factor count you expect; if burn-in adaptation saturates the
      cap the run warns and records the blocked-attempt counts in
      ``Posterior.nf_saturation`` — raise ``nf_cap`` and refit then.
    - ``record_dtype`` (e.g. ``jnp.bfloat16``) quantises recorded draws
      before the device->host fetch, halving posterior transfer bytes; the
      in-sweep state stays f32 (the chain itself is unaffected) and draws
      are widened back to f32 on the host.  bf16 keeps f32 range with ~3
      significant digits — well below Monte-Carlo error for summary use, but
      the default (``None``) records exact f32 draws.
    - ``record=("Beta", "Lambda", ...)`` restricts which parameters are
      recorded (default: everything, like the reference).  On a
      remote-attached device the posterior transfer is the dominant
      end-to-end cost at scale, and e.g. Eta at np=1000+ units is the
      largest block while CV / WAIC / variance partitioning never read it.
      Accepts base names (applied across levels) or per-level names
      (``"Eta_0"``); Beta and the nfMask bookkeeping are always kept, and
      sign-alignment references are force-included (Lambda whenever the
      corresponding Eta is recorded; wRRR on reduced-rank models).
      Un-recorded parameters raise a clear KeyError downstream.
    - ``checkpoint_every=N`` with ``checkpoint_path=DIR`` writes a resumable
      snapshot every N recorded samples.  With the default
      ``checkpoint_layout="append"`` a snapshot is O(segment), flat in run
      length: the draws recorded since the previous snapshot are flushed
      once into an immutable ``seg-<proc>-<first>-<last>.npz`` shard, the
      carry state + RNG keys land in a small ``state-<samples>.npz``, and an
      atomically-renamed ``manifest-<samples>.json`` (per-payload crc32
      checksums, spec fingerprint) is the commit point — total checkpoint
      bytes over a run are O(S) instead of the self-contained layout's
      O(S²).  ``checkpoint_layout="rotating"`` keeps the legacy
      self-contained ``ckpt-<samples>.npz`` files (each holding all draws so
      far); both layouts load via the same ``resume_run`` /
      ``load_checkpoint``.  Snapshots land on host-segment boundaries — the
      same segmentation machinery ``verbose`` uses — so the key stream (and
      therefore every draw) is bit-identical for any checkpointing cadence
      and either layout.  While active, SIGTERM/SIGINT is
      intercepted: the in-flight segment finishes, a final snapshot is
      written, and the run unwinds with
      :class:`~hmsc_tpu.utils.checkpoint.PreemptedRun`.  Continue with
      :func:`~hmsc_tpu.utils.checkpoint.resume_run` (or
      ``python -m hmsc_tpu run --resume``), which restores the key stream so
      kill → resume reproduces the uninterrupted run exactly.
      ``checkpoint_path`` alone (no ``checkpoint_every``) writes a single
      snapshot at completion.  While checkpointing (or ``verbose``) is on,
      the *transient* scan is segmented too: burn-in reports progress and
      writes resumable state-only snapshots (``manifest-t<sweep>.json`` /
      legacy ``ckpt-t<sweep>.npz`` — carry state + RNG keys, no draws), so a
      kill during a long burn-in no longer loses it.
    - ``checkpoint_keep`` rotates the newest K snapshots (under the append
      layout rotation deletes *manifests*; shards referenced by no surviving
      manifest are garbage-collected); ``checkpoint_max_age_s`` additionally
      deletes kept snapshots older than the given age (the newest always
      survives); ``checkpoint_max_bytes`` bounds the layout's total on-disk
      bytes, dropping the oldest snapshots first (never the newest); and
      ``checkpoint_archive_every=N`` hard-links every Nth written snapshot
      into ``<checkpoint_path>/archive/`` exempt from rotation and GC
      (post-hoc divergence debugging: old snapshots stay inspectable after
      the rotation window has moved on).  With the append layout,
      ``retry_diverged`` warm-restarts a diverged chain from the last
      manifest at which it was still healthy — keeping its healthy draws
      and re-running only the remainder — instead of repeating the full
      burn-in from scratch (the cold restart remains the fallback when no
      healthy snapshot exists).
    - ``pipeline`` (default on) runs the host loop as a pipeline: the
      jitted segment runner *donates* its carry buffers (the scan carry is
      updated in place — one copy of the state pytree in HBM instead of
      two), the device→host fetch of each packed sample segment is consumed
      by a background writer thread while the next segment computes, and
      checkpoint serialisation + atomic rename happen on that same thread.
      The queue between the loop and the writer is bounded
      (``pipeline_depth`` segments) with blocking backpressure, so a slow
      disk cannot grow host memory without bound; writer failures propagate
      to the caller, and an in-flight/fsync barrier runs at preemption and
      at run end so the durability and bit-identical-resume guarantees are
      unchanged.  The draw stream is device-side only, so draws are
      bit-identical with the pipeline on or off; ``pipeline=False`` keeps
      the fully serialised loop.  Per-run host-loop counters land in
      ``Posterior.io_stats``.
    - ``init_keys`` resumes the per-chain RNG key stream from a checkpoint
      (requires ``init_state``); without it a resumed run draws a fresh
      stream seeded from (seed, carried iteration).
    - ``shard_sweep`` controls WITHIN-model parallelism when ``mesh`` names
      a species axis (and optionally a ``site_axis``) of extent > 1.  The
      default (``None``, auto) wraps
      the whole Gibbs sweep in ``jax.experimental.shard_map`` over the
      mesh: every species-dimensioned carry/data array is sharded per the
      committed PartitionSpec tables in :mod:`hmsc_tpu.mcmc.partition`,
      per-species blocks (Beta/Lambda/Z/sigma) run fully local, and only
      the few cross-species reductions (updateEta's factor grams,
      GammaV's ``B``-products, the rho quadratic, Nf statistics,
      divergence tracking) are explicit psum/all_gather collectives — so
      per-device state shrinks ~1/shards and the one-chip ceiling on
      ``ns`` breaks.  A 2D mesh (``make_mesh(species_shards=k,
      site_shards=m)``) additionally shards the SITE axis: Z's rows,
      per-level Eta rows, the row data, and the NNGP/GPP per-unit
      structure grids split over ``m`` site shards, with per-unit
      spatial solves on local unit blocks, explicit collectives at the
      cross-site reductions (design grams, updateZ column statistics,
      Alpha grid quadratics, GPP knot corrections, divergence tracking
      over both axes), and explicit Eta row gathers wherever a ``Pi``
      row read crosses shards — breaking the per-device Eta ceiling of
      np-dominated spatial models.  Every species- and site-dimensioned
      random draw is taken at
      the global width and sliced, keeping the sharded draw stream equal
      to the replicated sweep's; agreement is within the documented
      tolerance (``partition.SHARD_AGREEMENT_TOL``, psum rounding only).
      Models the sharded sweep cannot express (dense-phylo fallbacks, the
      opt-in collapsed updaters) auto-fall back to GSPMD placement with a
      warning — and classes without a site-sharded formulation
      (per-species X lists, selection/RRR, xDim > 0 levels, an active
      ``precision_policy``), or non-divisible ``ny``/unit counts, fall
      back to species-only sharding naming the nearest valid site
      divisor; ``True`` makes the fallbacks errors, ``False`` always
      uses legacy GSPMD placement.  Resume of a sharded run may
      re-shard freely — the
      committed draws are layout-independent within the same tolerance
      (checkpoint metadata records the engaged ``(species_shards,
      site_shards)`` tuple; with ``local_rng=True`` resume pins BOTH).
    - ``coordinator`` scales chains across a multi-process mesh (the
      reference's SOCK-cluster ``nParallel``, re-architected): ``n_chains``
      is the GLOBAL count, process ``p`` of ``R`` samples the contiguous
      chain slice ``[p·n/R, (p+1)·n/R)`` with seeds derived from the global
      chain index — so the per-chain draw stream is bit-identical for ANY
      process count, including single-process.  Chains never communicate;
      processes coordinate only at checkpoint boundaries: each appends its
      own ``seg-<proc>-…`` shard stream and ``state-<tag>-p<proc>.npz``
      carry slice, a barrier certifies every process durably fsynced up to
      the boundary, then process 0 alone commits the stitched
      ``manifest-<tag>.json`` (and alone runs GC, which never reclaims a
      peer's uncommitted newest shards).  SIGTERM on ANY process rides the
      next boundary's gather, so every process unwinds resumably at the
      same committed boundary.  Each process returns the Posterior of its
      OWN chain slice; the committed manifest holds the global run
      (``load_manifest_checkpoint`` / ``resume_run`` — which re-shards the
      chains when the process count changes).  Defaults to
      ``jax.distributed`` auto-detection; pass a
      :class:`~hmsc_tpu.utils.coordination.FileCoordinator` to run the
      full protocol over a shared filesystem (or in tests, subprocesses).
      Multi-process runs require ``checkpoint_layout="append"``;
      ``from_prior`` is single-process-only.  ``retry_diverged`` on a
      multi-process mesh runs the COORDINATED splice (and therefore
      requires checkpointing): an end-of-run health gather agrees on the
      diverged chain set, every rank identifies the same last-healthy
      manifest, the owning rank(s) warm-restart their chains
      single-process from that shared boundary, and the repair shard is
      committed there by the ordinary coordinated commit — healthy
      ranks' draws are untouched bit-for-bit.
    - ``telemetry`` controls the run-telemetry subsystem
      (:mod:`hmsc_tpu.obs`): every run keeps in-memory span/health
      aggregates (surfaced as ``Posterior.telemetry`` and the
      ``io_stats`` view), and a checkpointed run additionally writes a
      structured, rank-tagged JSONL event stream
      (``events-p<rank>.jsonl``, next to the snapshots, flushed on the
      background writer so it never sits on the segment loop) — host-loop
      spans (compile / dispatch / device→host fetch / shard, state and
      manifest writes / barrier waits / GC / splice repairs), per-segment
      MCMC health metrics (draws/sec, divergence counters, nf-adaptation
      trajectory, running R-hat/ESS over a small monitored subset), and —
      on a multi-process mesh — committer-recorded cross-rank skew riding
      the commit gather.  ``None`` (default) auto-enables the stream
      whenever checkpointing is on; a path enables it into that directory
      for any run; ``True`` insists on recording (an error when there is
      no checkpoint directory or path to write to); ``False`` disables
      event recording entirely (the cheap aggregates remain).  Telemetry only ever sees host-side copies, so
      the draw stream is bit-identical with it on, off, or at any cadence.
      Render a recorded run with ``python -m hmsc_tpu report <run_dir>``.
    - ``profile_segments=(start, stop)`` (with ``profile_dir``) captures a
      ``jax.profiler`` trace covering only host segments ``start..stop``
      (inclusive) — the deep-dive window for a stall telemetry located —
      instead of ``profile_dir`` alone's whole-run trace.
    - ``profile_updaters=N`` records ONE instrumented per-updater profile
      pass at the first segment boundary at or after sweep ``N``
      (clamped to the run's last sweep): the live carry is swept once more
      with every Gibbs block dispatched as its own jitted call and timed
      (:func:`instrumented_sweep`), and the per-updater wall/share table is
      emitted as an ``updater_profile`` telemetry metric and surfaced as
      ``Posterior.updater_profile``.  The pass only *reads* the carry —
      its re-run of the next sweep is discarded — so the production fused
      runner's draw stream is bit-identical with profiling on or off
      (pinned by ``tests/test_profile.py``).  Render with
      ``python -m hmsc_tpu report`` ("cost attribution" section).
    - ``progress_callback(samples_done, samples_total)`` is invoked on the
      host after every compiled segment (cumulative counts when continuing a
      checkpointed run; burn-in segments report ``samples_done`` still at
      its pre-sampling value); exceptions propagate and abort the run — the
      fault-injection harness uses this to simulate device loss.  Any
      checkpoint already submitted for the boundary is drained to disk
      before the error escapes.
    - ``precision_policy`` engages the per-block mixed-precision engine
      (:mod:`hmsc_tpu.mcmc.precision`): ``"auto"`` selects the
      ledger-driven default for this model class (the top wall-share
      Gibbs blocks compute their heavy dots and grams bf16 with f32
      accumulation, their sweep-invariant model-data operands are staged
      to bf16 once per run, and the fused batched Cholesky layouts
      activate); a :class:`~hmsc_tpu.mcmc.precision.PrecisionPolicy`
      customises the block set.  Reductions and Cholesky/solve pivots
      stay f32-pinned.  The draw stream is NOT the f32 stream: one-sweep
      agreement is within the pinned
      ``precision.PRECISION_AGREEMENT_TOL`` with per-block measurements
      recorded in the committed ``precision_tolerance.json`` (the
      training-side mirror of ``compact --dtype bfloat16``'s recorded
      cast tolerance).  The default ``None`` is the exact pre-policy
      engine — traced programs byte-identical to the committed
      fingerprints.  The policy is stored in checkpoint metadata and
      restored on resume (it changes the stream, so it is not
      overridable there).
    - ``local_rng=True`` (opt-in, requires the species-sharded sweep)
      folds the shard index into the key and draws species-dim randoms
      at O(ns_local) width instead of the default full-width-and-slice.
      This trades the replicated-draw equality contract (sharded vs
      replicated runs then agree only in distribution) for draw cost —
      the full-width draws are the main weak-scaling overhead at
      RNG-bound sizes.  Determinism is unchanged: same mesh/seed
      reproduces the same stream, and kill -> resume stays
      bit-identical.
    """
    import time

    from ..post.posterior import Posterior
    from ..utils.coordination import get_coordinator

    t0 = time.perf_counter()

    # multi-process chain sharding: `n_chains` stays the GLOBAL count; this
    # process samples its contiguous slice with seeds derived from the
    # global chain index, so the per-chain draw stream is independent of
    # the process layout (resume may re-shard freely)
    coord = get_coordinator(coordinator)
    n_procs = int(coord.process_count)
    proc = int(coord.process_index)
    n_chains = int(n_chains)
    if n_chains % n_procs:
        raise ValueError(
            f"n_chains={n_chains} must be a multiple of the coordinator's "
            f"process_count ({n_procs}) so chains shard evenly over "
            "processes")
    n_local = n_chains // n_procs
    chains_lo = proc * n_local
    # XLA batch-shape guard: vmapping ONE chain compiles a different
    # (degenerate-batch) program than vmapping several, and its float32
    # stream differs from the batched one at ULP level — which would break
    # the layout-invariance contract exactly when R == n_chains.  A
    # single-chain process therefore runs a 2-lane batch with its chain
    # DUPLICATED (lanes never interact, so lane 0's stream is untouched
    # and both lanes are bit-identical); the duplicate lane is sliced away
    # before anything leaves the device (records, carry snapshots, the
    # returned posterior).
    n_dup = 1 if (n_procs > 1 and n_local == 1) else 0
    n_batch = n_local + n_dup
    if n_procs > 1:
        if retry_diverged and checkpoint_path is None:
            raise ValueError(
                "retry_diverged under a multi-process coordinator requires "
                "append-layout checkpointing (checkpoint_path=...): the "
                "coordinated splice unwinds every rank to a shared "
                "last-healthy manifest boundary and commits the repair "
                "shard there")
        if from_prior:
            raise ValueError("from_prior does not shard over a "
                             "multi-process coordinator")
        if checkpoint_path is not None and checkpoint_layout != "append":
            raise ValueError(
                "multi-process checkpointing requires "
                "checkpoint_layout='append' (the rotating self-contained "
                "format has no per-process commit point)")

    # run telemetry (hmsc_tpu.obs): the aggregator always runs (io_stats
    # and the multi-process skew gather are derived from it); JSONL event
    # recording is what `telemetry=False` turns off.  A sink is attached
    # below once the run directory is known.
    from ..obs import (RunTelemetry, RunningDiagnostics, SCHEMA_VERSION,
                       events_path, get_logger)
    if not (telemetry is None or isinstance(telemetry, (bool, str))
            or hasattr(telemetry, "__fspath__")):
        raise ValueError("telemetry must be None, a bool, or a directory "
                         f"path, got {telemetry!r}")
    telem = RunTelemetry(proc=proc, enabled=telemetry is not False)
    log = get_logger(telemetry=telem, proc=proc, n_procs=n_procs)
    if profile_segments is not None:
        if profile_dir is None:
            raise ValueError("profile_segments requires profile_dir (the "
                             "trace output directory)")
        profile_segments = (int(profile_segments[0]),
                            int(profile_segments[1]))
        if not (0 <= profile_segments[0] <= profile_segments[1]):
            raise ValueError("profile_segments must be (start, stop) with "
                             f"0 <= start <= stop, got {profile_segments}")
    if profile_updaters is not None:
        profile_updaters = int(profile_updaters)
        if profile_updaters < 0:
            raise ValueError("profile_updaters must be >= 0 (the sweep "
                             "index at which the instrumented per-updater "
                             f"pass records), got {profile_updaters}")

    adapt_nf_arg = adapt_nf          # pre-resolution value, for retry_diverged
    if adapt_nf is None:
        adapt_nf = tuple(transient for _ in range(hM.nr))
    else:
        adapt_nf = tuple(int(a) for a in np.broadcast_to(adapt_nf, (hM.nr,)))
    if any(a > transient for a in adapt_nf) and init_state is None:
        # a resumed continuation legitimately carries the original run's
        # adaptation window past its own transient=0: the adaptation gate
        # compares against the carried absolute iteration counter, so the
        # window is long since closed — and passing the original adapt_nf
        # lets the continuation reuse the original run's compiled program
        raise ValueError("transient parameter should be no less than any element of adaptNf parameter")

    spec = build_spec(hM, nf_cap)
    record = normalize_record(spec, record)
    if data_par is None:
        data_par = compute_data_parameters(hM)
    data = build_model_data(hM, data_par, spec, dtype=dtype)

    rng = np.random.default_rng(seed)
    # GLOBAL seed table sliced to this process's chains: chain c gets the
    # same seed under every process layout
    chain_seeds = rng.integers(0, 2**31 - 1,
                               size=n_chains)[chains_lo:chains_lo + n_local]
    if n_dup:
        chain_seeds = np.concatenate([chain_seeds, chain_seeds[:1]])

    if from_prior:
        from .prior import sample_prior_chains
        post = sample_prior_chains(hM, spec, data_par, samples, n_chains, rng)
        return Posterior(hM, spec, post, samples=samples, transient=transient,
                         thin=thin)

    it0 = 0
    if init_state is not None:
        state0 = init_state                       # (chains, ...) carry pytree
        lead = int(jax.tree.leaves(state0)[0].shape[0])
        if lead != n_local:
            raise ValueError(
                f"init_state carries {lead} chains, expected {n_local} "
                f"(n_chains={n_chains} over {n_procs} process(es))")
        it0 = int(np.asarray(state0.it).ravel()[0])
        # a resumed run must not replay the original run's key stream: mix
        # the carried iteration count into the seed derivation (global
        # table, then this process's slice — layout-invariant)
        rng = np.random.default_rng([0 if seed is None else int(seed), it0])
        chain_seeds = rng.integers(
            0, 2**31 - 1, size=n_chains)[chains_lo:chains_lo + n_local]
        if n_dup:
            chain_seeds = np.concatenate([chain_seeds, chain_seeds[:1]])
    else:
        states = [build_state(hM, spec, int(s), init_par, dtype=dtype)
                  for s in chain_seeds]
        state0 = jax.tree.map(lambda *xs: jnp.stack(xs), *states)
    # canonicalise carry leaves to strong dtypes: the stacked fresh state
    # otherwise carries weak-typed f32 scalars (the 0.0 RRR placeholders),
    # and a checkpoint-loaded state (strong f32 from disk) would miss the
    # compiled executable and pay a full recompile on every resume
    state0 = jax.tree.map(
        lambda x: jnp.asarray(x, dtype=x.dtype) if hasattr(x, "dtype") else x,
        state0)
    if init_state is not None:
        # the compiled runner donates its carry: the first segment would
        # consume (invalidate) the caller's init_state arrays — hand the
        # runner a private copy instead (duplicating the chain lane when
        # the single-chain batch guard applies)
        _cp = ((lambda x: jnp.concatenate([x, x[:1]], axis=0)) if n_dup
               else jnp.copy)
        state0 = jax.tree.map(
            lambda x: _cp(x) if isinstance(x, jax.Array) else x, state0)

    # structural gates for the opt-in collapsed updaters (reference
    # auto-gating, sampleMcmc.R:123-152; see updaters_marginal)
    if updater and (updater.get("Gamma2") is True
                    or updater.get("GammaEta") is True):
        from .updaters_marginal import gamma_eta_gates
        gates = gamma_eta_gates(spec, mGamma=hM.mGamma)
        updater = dict(updater)
        for name in ("Gamma2", "GammaEta"):
            if updater.get(name) is True and gates[name]:
                log.info(f"Setting updater${name}=FALSE: {gates[name]}")
                updater[name] = False

    # structural gate for the opt-in location interweave (same print-style
    # as the collapsed-updater gates above, so a silent no-op can't be
    # mistaken for "the move doesn't help")
    if updater and updater.get("InterweaveLocation") is True:
        from .updaters import location_gate
        reason = location_gate(spec,
                               has_intercept=data.x_ones_ind is not None)
        if reason:
            log.info(f"Setting updater$InterweaveLocation=FALSE: {reason}")
            updater = dict(updater)
            updater["InterweaveLocation"] = False

    # structural gate for the opt-in probit-DA intercept interweave
    if updater and updater.get("InterweaveDA") is True:
        from .updaters import da_intercept_gate
        reason = da_intercept_gate(
            spec, has_intercept=data.x_ones_ind is not None)
        if reason:
            log.info(f"Setting updater$InterweaveDA=FALSE: {reason}")
            updater = dict(updater)
            updater["InterweaveDA"] = False

    # per-block mixed-precision policy (mcmc/precision.py), resolved
    # against the final spec so the ledger-driven "auto" selection sees
    # the model class (and block applicability) it will actually run
    from .precision import resolve_policy, stage_data
    policy = resolve_policy(precision_policy, spec)
    if policy is not None and profile_updaters is not None:
        raise ValueError(
            "profile_updaters is unsupported with a precision_policy: the "
            "instrumented per-block pass runs the exact f32 schedule and "
            "would mis-attribute the policy'd sweep — profile the f32 run, "
            "or use the cost ledger's policy columns "
            "(`python -m hmsc_tpu profile --static`)")
    local_rng = bool(local_rng)

    updater_items = (tuple(sorted(updater.items())) if updater else None)
    sharding = None
    runner_mesh = None                    # engages the shard_map sweep path
    runner_site_axis = None               # site axis engaged on that mesh
    shard_meta = {"species_shards": None, "site_shards": None}
    if shard_sweep not in (None, True, False):
        raise ValueError(f"shard_sweep must be None (auto), True or False, "
                         f"got {shard_sweep!r}")
    _axes = getattr(mesh, "axis_names", ()) if mesh is not None else ()
    _sp_ext = int(mesh.shape[species_axis]) if species_axis in _axes else 1
    _st_ext = int(mesh.shape[site_axis]) if site_axis in _axes else 1
    # a site axis only counts toward strict mode when the species axis
    # exists alongside it (the 2D geometry hangs off the species ctx;
    # make_mesh(site_shards=m) always emits both) — without this, a
    # hand-built (chains, sites) mesh would pass the check here and
    # then silently replicate when the site gate drops the orphan axis
    if shard_sweep is True and mesh is not None and _sp_ext < 2 \
            and (_st_ext < 2 or species_axis not in _axes):
        # strict mode needs something to shard OVER: silently replicating
        # here would defeat the 1/shards per-device state the caller
        # explicitly asked for
        raise ValueError(
            "shard_sweep=True requires a mesh with a "
            f"'{species_axis}' (or '{site_axis}') axis of extent >= 2 — a "
            f"'{site_axis}' axis also needs the '{species_axis}' axis "
            f"alongside it (got {tuple(mesh.shape.items())}) "
            "— build one with make_mesh(species_shards=k) / "
            "make_mesh(..., site_shards=m)")
    if shard_sweep is True and mesh is None:
        raise ValueError(
            "shard_sweep=True requires a mesh with a "
            f"'{species_axis}' axis of extent >= 2 (got no mesh) "
            "— build one with make_mesh(species_shards=k)")
    if mesh is not None:
        # chains are the data-parallel axis; if the mesh also names a
        # `species_axis`, the species dimension of every site x species array
        # is sharded over it (model parallelism).  Default (shard_sweep=
        # None/True): the sweep itself is shard_map'd over the species
        # axis — per-species blocks run fully local and the few
        # cross-species reductions (the factor grams in updateEta, E E'
        # in updateGammaV, the rho quadratic, Nf statistics, divergence
        # tracking) are explicit psum/all_gather collectives with
        # committed PartitionSpecs (mcmc/partition.py), so per-device
        # carry state shrinks ~1/shards.  shard_sweep=False keeps the
        # legacy GSPMD placement (XLA chooses the collectives).  This
        # replaces the reference's chains-only SOCK parallelism with
        # dp x tp over one mesh.
        from jax.sharding import NamedSharding, PartitionSpec as P
        n_chain_devs = int(mesh.shape[chain_axis])
        if n_local % n_chain_devs:
            raise ValueError(
                f"{n_local} per-process chain(s) must be a multiple of the "
                f"mesh's '{chain_axis}' extent ({n_chain_devs}) so chains "
                "lay out evenly over devices")
        sp = species_axis if species_axis in mesh.axis_names else None
        if sp is not None and spec.ns % int(mesh.shape[sp]) != 0:
            from .partition import nearest_divisor
            n_sp = int(mesh.shape[sp])
            msg = (f"mesh names a '{sp}' axis of size {n_sp} but "
                   f"ns={spec.ns} is not divisible by "
                   f"species_shards={n_sp}; the nearest valid "
                   f"species_shards for ns={spec.ns} is "
                   f"{nearest_divisor(spec.ns, n_sp)} (pad or regroup "
                   "species to use another)")
            if shard_sweep is True:
                # strict mode: an explicit request to shard must not
                # silently replicate — the whole point was the 1/shards
                # per-device state
                raise ValueError(f"shard_sweep=True but {msg}")
            # per-invocation dedup: the fallback used to warn once per
            # warnings-registry state, segment cadence permitting; one
            # warning per sample_mcmc call is the signal.  A retry /
            # continuation SUB-call builds its own logger and warns afresh
            # — it is a new sampling run of the same program
            log.warn_once(
                "shard-divisibility",
                f"{msg}; species arrays are replicated (chains-only "
                "parallelism)")
            sp = None
        # the SITE axis (2D mesh): rows + per-level units must divide the
        # extent, and the model class must have a site-sharded
        # formulation; either failure falls back to species-only
        # sharding with a warning (never silent replication)
        st = site_axis if (site_axis in mesh.axis_names
                           and int(mesh.shape[site_axis]) > 1
                           and species_axis in mesh.axis_names) else None
        if st is not None and sp is None and _sp_ext > 1:
            # a species fallback leaves arrays replicated over a >1
            # species axis — the shard_map geometry cannot express that,
            # so the site axis falls back with it
            st = None
        if st is not None:
            n_st = int(mesh.shape[st])
            bad_np = [int(ls.n_units) for ls in spec.levels
                      if ls.n_units % n_st]
            if spec.ny % n_st or bad_np:
                from .partition import nearest_site_divisor
                what = (f"ny={spec.ny}" if spec.ny % n_st
                        else f"a level's unit count np={bad_np[0]}")
                near = nearest_site_divisor(
                    spec.ny, [ls.n_units for ls in spec.levels], n_st)
                msg = (f"mesh names a '{st}' axis of size {n_st} but "
                       f"{what} is not divisible by site_shards={n_st}; "
                       f"the nearest valid site_shards for this model is "
                       f"{near}")
                if shard_sweep is True:
                    raise ValueError(f"shard_sweep=True but {msg}")
                log.warn_once(
                    "site-shard-divisibility",
                    f"{msg}; site arrays are replicated (species-only "
                    "model parallelism)")
                st = None
        if st is not None:
            from .partition import site_shard_unsupported_reason
            reason = site_shard_unsupported_reason(spec, updater)
            if reason is not None:
                if shard_sweep is True and _sp_ext < 2:
                    raise ValueError(
                        f"shard_sweep=True but the site-sharded sweep "
                        f"does not support this model: {reason}")
                log.warn_once(
                    "site-shard-unsupported",
                    f"site-sharded sweep unavailable for this model "
                    f"({reason}); falling back to species-only sharding")
                st = None
        want_shard = (((sp is not None and int(mesh.shape[sp]) > 1)
                       or st is not None)
                      and shard_sweep is not False)
        if want_shard:
            from .partition import shard_unsupported_reason
            reason = shard_unsupported_reason(spec, updater)
            if reason is not None:
                if shard_sweep is True:
                    raise ValueError(
                        f"shard_sweep=True but the species-sharded sweep "
                        f"does not support this model: {reason}")
                log.warn_once(
                    "shard-unsupported",
                    f"species-sharded sweep unavailable for this model "
                    f"({reason}); falling back to GSPMD placement")
                want_shard = False
        sharding = NamedSharding(mesh, P(chain_axis))
        if want_shard:
            from .partition import (DATA_SITE_DIMS, DATA_SPECIES_DIMS,
                                    STATE_SITE_DIMS, STATE_SPECIES_DIMS,
                                    place_on_mesh)
            runner_mesh = mesh
            runner_site_axis = st
            shard_meta = {
                "species_shards": int(mesh.shape[sp]) if sp is not None
                else 1,
                "site_shards": int(mesh.shape[st]) if st is not None else 1,
            }
            state0 = place_on_mesh(state0, mesh, spec, sp or species_axis,
                                   STATE_SPECIES_DIMS, lead=chain_axis,
                                   site_axis=st,
                                   site_dims=STATE_SITE_DIMS if st else None)
            data = place_on_mesh(data, mesh, spec, sp or species_axis,
                                 DATA_SPECIES_DIMS,
                                 x_is_list=spec.x_is_list, site_axis=st,
                                 site_dims=DATA_SITE_DIMS if st else None)
        else:
            state0 = _shard_species(state0, mesh, spec, sp, lead=chain_axis)
            if sp is not None:
                data = _shard_species(data, mesh, spec, sp, lead=None)
    if local_rng and runner_mesh is None:
        raise ValueError(
            "local_rng=True requires the species-sharded sweep (a mesh "
            "with a species axis of extent >= 2 and a shardable model) — "
            "on the replicated sweep there is no shard to localise the "
            "draws to")

    # the policy's staged bf16 shadow table: cast ONCE here (after any
    # mesh placement, so the shadows inherit their originals' sharding)
    # and passed to every segment as a real, undonated runner argument
    staged_tbl = stage_data(data, policy) if policy is not None else None

    # progress printing and auto-checkpointing both split the sample scan
    # into host-level segments (the reference's per-iteration printout,
    # sampleMcmc.R:317-324, at `verbose`-sweep granularity; snapshots at
    # `checkpoint_every`-sample granularity).  The carried key makes the
    # draw stream identical for ANY segmentation, so the boundary set is
    # simply the union of what either feature needs.  (Measured: on a
    # remote-attached chip, device->host copies do not overlap device
    # compute, so segmentation adds only per-segment round-trip latency —
    # with both features off the scan stays one segment.)
    ck_every = int(checkpoint_every or 0)
    if ck_every < 0:
        raise ValueError(f"checkpoint_every must be >= 0, got {ck_every}")
    if ck_every and checkpoint_path is None:
        raise ValueError("checkpoint_every requires checkpoint_path "
                         "(a directory for the rotating snapshots)")
    archive_every = int(checkpoint_archive_every or 0)
    if archive_every < 0:
        raise ValueError("checkpoint_archive_every must be >= 0, "
                         f"got {archive_every}")
    if checkpoint_layout not in ("append", "rotating"):
        raise ValueError("checkpoint_layout must be 'append' or 'rotating', "
                         f"got {checkpoint_layout!r}")
    if int(checkpoint_keep) < 0:
        raise ValueError("checkpoint_keep must be >= 0 (0 keeps every "
                         f"snapshot), got {checkpoint_keep}")
    if checkpoint_max_bytes is not None and int(checkpoint_max_bytes) < 1:
        raise ValueError("checkpoint_max_bytes must be >= 1, got "
                         f"{checkpoint_max_bytes}")
    append_layout = checkpoint_layout == "append"
    if checkpoint_path is not None and ck_every == 0:
        ck_every = int(samples)       # single snapshot at completion
    if int(samples) == 0:
        ck_every = 0                  # nothing recordable to snapshot
    marks = {int(samples)}
    if verbose:
        chunk = max(1, int(round(verbose / thin)))
        marks.update(range(chunk, int(samples), chunk))
    if ck_every:
        marks.update(range(ck_every, int(samples), ck_every))
    cuts = sorted(marks)
    seg_sizes = [b - a for a, b in zip([0] + cuts[:-1], cuts)]
    ck_marks = ({m for m in cuts if m % ck_every == 0} | {int(samples)}
                if ck_every else set())
    # burn-in segmentation (ROADMAP: a kill during a long transient used to
    # lose all of it): whenever a host boundary exists at all, the transient
    # scan is segmented on the same cadences — `verbose` sweeps for progress,
    # `checkpoint_every * thin` sweeps for state-only burn-in snapshots.  The
    # carried key makes this segmentation draw-invariant too.  With neither
    # feature on, the transient stays fused into the first sampling program.
    t_cuts, t_ck_marks = [], set()
    if int(transient) > 0 and (ck_every or verbose):
        t_marks = {int(transient)}
        if verbose:
            t_marks.update(range(int(verbose), int(transient), int(verbose)))
        if ck_every:
            t_step = max(1, ck_every * int(thin))
            t_marks.update(range(t_step, int(transient), t_step))
            t_ck_marks = {m for m in t_marks if m % t_step == 0}
        t_cuts = sorted(t_marks)
    # the segment plan: (transient sweeps, recorded samples) per compiled
    # chunk.  Pure burn-in segments record nothing (samples=0); the first
    # sampling segment carries any unsegmented transient remainder.
    plan = [(t, 0) for t in
            (b - a for a, b in zip([0] + t_cuts[:-1], t_cuts))]
    rem_transient = 0 if t_cuts else int(transient)
    plan += [(rem_transient if i == 0 else 0, s)
             for i, s in enumerate(seg_sizes)]
    total_it = it0 + int(transient) + int(samples) * int(thin)

    base_post = _ckpt_base            # prior segments of a resumed run
    base_samples = int(base_post.samples) if base_post is not None else 0
    ck_dir = None
    if ck_every:
        import os
        ck_dir = os.fspath(checkpoint_path)
        os.makedirs(ck_dir, exist_ok=True)
        if init_state is None and base_post is None:
            # a FRESH run owns its snapshot directory: stale snapshots from
            # an earlier run would outnumber this run's early snapshots and
            # resume_run would silently return the old run's posterior.
            # On a multi-process mesh only the committer clears (peers wait
            # at the barrier so none of them can write before the sweep).
            if n_procs == 1 or coord.is_coordinator:
                from ..utils.checkpoint import (_layout_files as _lf,
                                                checkpoint_files as _ck_files)
                stale = _ck_files(ck_dir)
                if stale:
                    import warnings
                    warnings.warn(
                        f"checkpoint_path {ck_dir!r} held {len(stale)} "
                        "snapshot(s) from a previous run; removing them so "
                        "resume_run cannot confuse the runs (use resume_run "
                        "instead of a fresh call to continue the old one)",
                        RuntimeWarning, stacklevel=2)
                # clear shards/state files too, not just resume candidates
                for p in (_lf(ck_dir) if stale else []):
                    try:
                        os.unlink(p)
                    except OSError:
                        pass
                # stale event streams go with them: a previous run's
                # events-p<r>.jsonl (possibly from MORE ranks than this
                # run has — each rank truncates only its own) would make
                # `report` merge dead ranks into the fresh run
                _sweep_stale_events(ck_dir)
            if n_procs > 1:
                coord.barrier("fresh-dir")

    # telemetry sink: events-p<rank>.jsonl next to the snapshots (or in the
    # explicitly given telemetry directory) — a fresh run truncates its own
    # rank's stream, a continuation appends to it.  Without a directory the
    # events stay in memory (aggregates only).
    tel_dir = None
    if telemetry is not False:
        if isinstance(telemetry, str) or hasattr(telemetry, "__fspath__"):
            import os
            tel_dir = os.fspath(telemetry)
        elif ck_dir is not None:
            tel_dir = ck_dir
    if telemetry is True and tel_dir is None:
        # an EXPLICIT request to record must not silently record nowhere
        raise ValueError(
            "telemetry=True needs somewhere to write the event stream: "
            "enable checkpointing (checkpoint_path=...) or pass the "
            "directory directly (telemetry='<dir>')")
    if tel_dir is not None:
        fresh = init_state is None and base_post is None
        if fresh and tel_dir != ck_dir and n_procs == 1:
            # explicit telemetry dir: same stale-rank sweep as the
            # checkpoint dir above (single-process only — multi-process
            # runs have no barrier protecting a non-checkpoint dir)
            _sweep_stale_events(tel_dir)
        telem.attach_sink(events_path(tel_dir, proc), truncate=fresh)
    # cross-process trace correlation (obs.trace, event-schema v2): a
    # sample_mcmc invocation is a top-level entry point — join the
    # spawning parent's trace when HMSC_TPU_TRACE_CTX carries one (fleet
    # worker, refit worker, job-queue bucket), otherwise mint a root.
    # Host-side entropy only; the draw stream never sees it.
    if telemetry is not False:
        from ..obs.trace import inherit_or_mint
        telem.set_trace(inherit_or_mint())
    telem.emit("run", "start", schema=SCHEMA_VERSION,
               samples=int(samples), transient=int(transient),
               thin=int(thin), n_chains=int(n_chains),
               process_count=n_procs,
               seed=None if seed is None else int(seed),
               checkpoint_every=ck_every,
               checkpoint_layout=checkpoint_layout if ck_every else None,
               pipeline=bool(pipeline), segments=len(seg_sizes) + len(t_cuts))

    # preemption-safe shutdown: while auto-checkpointing, SIGTERM/SIGINT set
    # a flag that the segment loop checks after each compiled chunk — finish
    # the segment, snapshot, unwind resumably.  A second signal escalates to
    # an immediate KeyboardInterrupt (escape hatch for a stuck segment).
    preempt = {"signum": None}
    restore_handlers = []
    if ck_every:
        import signal as _signal
        import threading as _threading
        if _threading.current_thread() is _threading.main_thread():
            def _on_signal(signum, frame):
                if preempt["signum"] is not None:
                    raise KeyboardInterrupt
                preempt["signum"] = signum
            for s in (_signal.SIGTERM, _signal.SIGINT):
                try:
                    restore_handlers.append((s, _signal.signal(s, _on_signal)))
                except (ValueError, OSError):
                    pass              # non-main thread / unsupported platform

    t1 = time.perf_counter()
    import contextlib
    # profile_segments narrows the capture to its own start/stop window in
    # the segment loop; the whole-run trace must stand down (the profiler
    # allows only one active capture)
    ctx = (jax.profiler.trace(profile_dir)
           if profile_dir is not None and profile_segments is None
           else contextlib.nullcontext())
    try:
      with ctx:
        import os

        host_segs = []                # fetched host record trees, in order
        state_cur = state0
        skip_z = init_state is not None
        bad_cur = jnp.full((n_batch,), -1, dtype=jnp.int32)
        if rng_impl is None:
            plat = jax.default_backend()
            rng_impl = "rbg" if ("tpu" in plat or "axon" in plat) \
                else "threefry2x32"
        # the per-chain key is threaded *through* the segments (the final
        # carry key of one segment seeds the next), so the draw stream is a
        # pure function of (seed, iteration) — identical for any `verbose`
        # or `checkpoint_every` segmentation (round-2 verdict weak #4)
        if init_keys is not None:
            if init_state is None:
                raise ValueError("init_keys requires init_state (both come "
                                 "from the same checkpoint)")
            if int(init_keys.shape[0]) != n_local:
                raise ValueError(
                    f"init_keys carries {int(init_keys.shape[0])} chain "
                    f"keys, expected {n_local} (n_chains={n_chains} over "
                    f"{n_procs} process(es))")
            # private copy: the donated carry must not consume the caller's
            keys = jnp.copy(init_keys)
            if n_dup:
                keys = jnp.concatenate([keys, keys[:1]])
        else:
            keys = jax.vmap(lambda s: jax.random.key(s, impl=rng_impl))(
                jnp.asarray(chain_seeds))
        if sharding is not None:
            keys = jax.device_put(keys, sharding)

        # the bounded background writer: segment fetches and checkpoint
        # serialisation run here while the next segment computes on-device
        writer = (_SegmentWriter(int(pipeline_depth)) if pipeline
                  else _InlineWriter())

        def _collect(packed, seg_idx):
            # the fetch span covers waiting out the segment's device
            # compute + the device→host copy (both happen at np.asarray
            # of the packed buffer, on this writer thread)
            with telem.span("fetch", seg=seg_idx):
                host_segs.append(_unpack_records(*packed))

        # per-segment MCMC health: throughput, divergence counters, the
        # nf-adaptation trajectory, and running R-hat/ESS over a small
        # monitored subset — computed host-side from the segment just
        # fetched, on the writer thread, so it never blocks the loop
        diag = RunningDiagnostics()
        health_t = {"t": time.perf_counter(), "armed": False}

        def _health(seg_idx, done_now, seg_samples, bad_snap):
            now = time.perf_counter()
            dt = max(now - health_t["t"], 1e-9)
            health_t["t"] = now
            try:
                seg_tree = host_segs[-1]
                diag.update(seg_tree)
                nf_act = {}
                for r in range(spec.nr):
                    mk = seg_tree.get(f"nfMask_{r}")
                    if mk is not None and np.size(mk):
                        nf_act[str(r)] = [
                            int(x) for x in np.asarray(mk)[:, -1].sum(-1)]
                n_bad = int((np.asarray(bad_snap) >= 0).sum())
                s = diag.summary()
                telem.emit(
                    "metric", "segment_health", seg=seg_idx,
                    samples_done=base_samples + int(done_now),
                    draws_per_s=round(n_local * int(seg_samples) / dt, 3),
                    diverged_chains=n_bad, nf_active=nf_act, **s)
            except Exception as e:    # noqa: BLE001 — observability must
                # never kill the run it observes
                telem.emit("log", "health_error",
                           text=f"{type(e).__name__}: {e}")

        def _merge_segs():
            if len(host_segs) > 1:    # fold so repeat snapshots stay linear
                merged = jax.tree.map(
                    lambda *xs: np.concatenate(xs, axis=1), *host_segs)
                host_segs[:] = [merged]

        def _snap_carry():
            """On-device copies of the carry for an in-flight checkpoint:
            the next segment DONATES (invalidates) the live carry buffers,
            so the writer thread must fetch from copies dispatched before
            that.  Keys are snapshotted as raw uint32 key data."""
            st = jax.tree.map(
                lambda x: (jnp.copy(x[:n_local]) if n_dup else jnp.copy(x))
                if isinstance(x, jax.Array) else x,
                state_cur)
            kd = jnp.array(jax.random.key_data(keys))[:n_local]
            return st, kd, jnp.copy(bad_cur[:n_local])

        def _run_meta(done_now):
            return {
                "samples_total": base_samples + int(samples),
                "samples_done": base_samples + done_now,
                "transient": int(base_post.transient if base_post is not None
                                 else _transient_base + int(transient)),
                "thin": int(thin), "n_chains": int(n_chains),
                "seed": None if seed is None else int(seed),
                "nf_cap": int(nf_cap), "rng_impl": rng_impl,
                "adapt_nf": [int(a) for a in adapt_nf],
                "dtype": np.dtype(dtype).name,
                "record": list(record) if record is not None else None,
                "record_dtype": (None if record_dtype is None
                                 else np.dtype(record_dtype).name),
                "updater": dict(updater) if updater else None,
                "retry_diverged": int(retry_diverged),
                "align_post": bool(align_post),
                "checkpoint_every": ck_every,
                "checkpoint_keep": int(checkpoint_keep),
                "checkpoint_max_age_s": checkpoint_max_age_s,
                "checkpoint_archive_every": archive_every,
                "checkpoint_max_bytes": checkpoint_max_bytes,
                "checkpoint_layout": checkpoint_layout,
                "process_count": n_procs,
                # both change the draw stream: resume restores them from
                # here, never from overrides
                "precision_policy": (policy.to_meta() if policy is not None
                                     else None),
                "local_rng": bool(local_rng),
                # the engaged mesh tuple (species_shards, site_shards) is
                # always recorded; a local_rng stream folds the shard
                # indices into the keys, so a continuation must re-shard
                # over the SAME extents on BOTH axes — resume_run checks
                # this when local_rng is set
                "species_shards": (shard_meta["species_shards"]
                                   if runner_mesh is not None else None),
                "site_shards": (shard_meta["site_shards"]
                                if runner_mesh is not None else None),
            }

        # ALL snapshot-write/layout logic lives in CheckpointWriter
        # (utils/checkpoint.py — unit-tested in isolation); the loop below
        # only snapshots the carry and submits.  The writer also runs the
        # multi-process commit protocol: gather-certified durability,
        # committer-only manifest + GC, coordinated preemption flags.
        ckw = None
        if ck_every:
            from ..utils.checkpoint import CheckpointWriter
            ckw = CheckpointWriter(
                ck_dir, checkpoint_layout, spec, hM=hM, records=host_segs,
                base_post=base_post, base_samples=base_samples,
                shards=_ckpt_shards, keep=int(checkpoint_keep),
                max_age_s=checkpoint_max_age_s, archive_every=archive_every,
                max_bytes=checkpoint_max_bytes, keys_impl=rng_impl,
                shard_index=(proc if n_procs > 1
                             else int(jax.process_index())),
                coordinator=coord if n_procs > 1 else None,
                preempt_fn=lambda: preempt["signum"] is not None,
                telemetry=telem)

        def _submit_ck(in_burnin, done_now, it_now):
            st, kd, bd = _snap_carry()
            meta = _run_meta(0 if in_burnin else done_now)
            writer.submit(functools.partial(
                ckw.snapshot, 0 if in_burnin else done_now, st, kd, bd,
                meta, burnin_it=it_now if in_burnin else None))
            return ckw.path_for(done_now,
                                burnin_it=it_now if in_burnin else None)

        done = 0
        sweeps_done = 0
        n_burn = len(t_cuts)          # leading plan entries are pure burn-in
        prof_on = False
        prof_done = False             # the window captures exactly once
        # instrumented per-updater pass: fires once, at the first segment
        # boundary at or after the requested sweep (clamped so an index
        # past the run still records at the final boundary)
        prof_upd = None
        prof_upd_at = (min(profile_updaters,
                           int(transient) + int(samples) * int(thin))
                       if profile_updaters is not None else None)
        for si, (trans_seg, seg) in enumerate(plan):
            in_burnin = si < n_burn
            if profile_segments is not None and not prof_on \
                    and not prof_done and si >= profile_segments[0]:
                # opt-in deep-dive window: trace only these host segments
                jax.profiler.start_trace(profile_dir)
                prof_on = True
                telem.emit("metric", "profile_capture", seg=si,
                           action="start")
            miss0 = _compiled_runner.cache_info().misses
            fn = _compiled_runner(spec, updater_items, adapt_nf, seg,
                                  trans_seg, int(thin), skip_z, record,
                                  spatial._NNGP_DENSE_MAX,
                                  mesh=runner_mesh, chain_axis=chain_axis,
                                  species_axis=species_axis,
                                  precision=policy, local_rng=local_rng,
                                  site_axis=runner_site_axis)
            # a cache miss means this static config is new to the process:
            # the dispatch below pays XLA trace + compile synchronously —
            # name the span for what it spends its time on
            fresh = _compiled_runner.cache_info().misses > miss0
            with telem.span("compile" if fresh else "dispatch", seg=si):
                args = (data, state_cur, keys, bad_cur)
                if policy is not None:
                    args = args + (staged_tbl,)
                recs, state_cur, bad_cur, keys = fn(*args)
            skip_z = True
            sweeps_done += trans_seg + int(seg) * int(thin)
            if not in_burnin:
                # pack now (async on device); the writer thread forces the
                # device→host fetch while the next segment computes.  Drop
                # the original record tree immediately — keeping it alive
                # through the fetch would double record HBM (the pack holds
                # the only live copy)
                if n_dup:             # drop the duplicate guard lane on
                    recs = jax.tree.map(lambda x: x[:n_local], recs)  # device
                if telem.enabled and not health_t["armed"]:
                    # start the throughput clock at the first sampling
                    # segment's submission (on the writer, FIFO before its
                    # fetch): the first draws/s point must not span
                    # burn-in + compile
                    health_t["armed"] = True
                    writer.submit(lambda: health_t.update(
                        t=time.perf_counter()))
                with telem.span("submit_wait", seg=si):   # ≈0 unless the
                    # bounded queue is full: measured time IS backpressure
                    writer.submit(functools.partial(
                        _collect, _pack_records(recs, record_dtype), si))
                del recs
                done += int(seg)
                if telem.enabled:
                    # per-segment health costs a device copy of the
                    # divergence tracker + a host R-hat/ESS pass —
                    # telemetry=False opts out of it along with the event
                    # stream (so the bench A/B measures the real cost).
                    # The copy is dispatched BEFORE the next segment
                    # donates bad_cur's buffer; the writer reads the copy.
                    bad_snap = jnp.copy(bad_cur[:n_local])
                    writer.submit(functools.partial(
                        _health, si, done, int(seg), bad_snap))
            if profile_segments is not None and prof_on \
                    and si >= profile_segments[1]:
                jax.profiler.stop_trace()
                prof_on = False
                prof_done = True
                telem.emit("metric", "profile_capture", seg=si,
                           action="stop")
            if prof_upd_at is not None and prof_upd is None \
                    and sweeps_done >= prof_upd_at:
                # one instrumented per-updater pass on the live carry: pure
                # reads, synchronous on the driver thread (the next
                # segment's donation only happens after this returns), so
                # the production draw stream is untouched.  The pass
                # profiles exactly the sweep the fused runner would do
                # next: the same per-chain subkey the scan's one_iter
                # would derive.
                # time_fused=False: the fused reference would compile a
                # standalone vmapped sweep (a program the run never
                # otherwise builds) on the driver thread mid-run — minutes
                # at scale, for one denominator.  The per-updater table
                # stands alone here; the CLI's measured mode carries the
                # fused comparison.
                with telem.span("updater_profile", seg=si):
                    subs = jax.jit(jax.vmap(
                        lambda k: jax.random.split(k)[1]))(keys)
                    _, prof_upd = instrumented_sweep(
                        spec, data, state_cur, subs, updater=updater,
                        adapt_nf=adapt_nf, vmapped=True, time_fused=False)
                prof_upd = dict(prof_upd, seg=si,
                                sweep=it0 + sweeps_done,
                                n_chains=int(n_batch))
                telem.emit("metric", "updater_profile", **prof_upd)
            if verbose:
                it_now = it0 + sweeps_done
                phase = ("sampling" if it_now > it0 + int(transient)
                         else "transient")
                log.info(f"iteration {it_now} of {total_it} ({phase})")
            wrote = None
            at_mark = (sweeps_done in t_ck_marks if in_burnin
                       else done in ck_marks)
            # single-process preemption snapshots at ANY segment boundary;
            # a multi-process run defers to the next *scheduled* checkpoint
            # mark — the last boundary COMMON to every process — where the
            # commit gather carries the preemption flags
            if ck_every and (at_mark or (preempt["signum"] is not None
                                         and n_procs == 1)):
                if n_procs > 1:
                    # coordinated commits are pipelined by ONE mark: drain
                    # the PREVIOUS commit here (it overlapped the segment
                    # that just finished — shard flush, gather, manifest
                    # all off the critical path, like the single-process
                    # writer) and read the abort verdict its gather
                    # carried.  Every process reads commit k's verdict at
                    # mark k+1, so a preemption (or a dead peer, surfacing
                    # as CoordinationError at this drain) still unwinds
                    # every process at the SAME committed boundary.
                    writer.barrier()
                wrote = _submit_ck(in_burnin, done, it0 + sweeps_done)
            if telem.has_sink:
                # drain buffered events to disk on the writer thread (FIFO
                # after this segment's fetch/snapshot items), keeping the
                # stream readable for an in-flight `report`
                writer.submit(telem.flush)
            if progress_callback is not None:
                progress_callback(base_samples + done,
                                  base_samples + int(samples))
            # the abort verdict is SET by the background writer whenever a
            # commit's gather completes — mid-segment, at rank-dependent
            # times.  Act on it only at marks (right after the drain above),
            # where every rank deterministically reads commit k's verdict at
            # mark k+1: acting at a finer verbose-only boundary would
            # snapshot at whatever `done` each rank happened to be at,
            # mispairing the coordinated commit's collectives.
            peer_abort = (ckw is not None and ckw.abort_agreed
                          and (n_procs == 1 or at_mark))
            if (preempt["signum"] is not None and n_procs == 1) or peer_abort:
                if ck_every and wrote is None:
                    wrote = _submit_ck(in_burnin, done, it0 + sweeps_done)
                # durability barrier: the snapshot (and every pending write)
                # is fsync-complete before the preemption unwinds
                writer.barrier()
                from ..utils.checkpoint import PreemptedRun
                progress = (f"{it0 + sweeps_done} of {total_it} burn-in "
                            "sweeps" if in_burnin else
                            f"{base_samples + done} of "
                            f"{base_samples + int(samples)} recorded samples")
                whom = (f"signal {preempt['signum']}"
                        if preempt["signum"] is not None
                        else "a preempted peer process")
                telem.emit("run", "preempted",
                           samples_done=base_samples + done,
                           signum=preempt["signum"])
                telem.flush()
                raise PreemptedRun(
                    f"run preempted by {whom} after "
                    f"{progress}; resumable checkpoint: {wrote} "
                    "(continue with resume_run or "
                    "`python -m hmsc_tpu run --resume`)",
                    checkpoint_path=wrote,
                    samples_done=base_samples + done,
                    signum=preempt["signum"])
        final_state = state_cur
        if n_dup:                     # shed the duplicate guard lane: the
            # posterior, health report and returned carry hold real chains
            final_state = jax.tree.map(
                lambda x: x[:n_local] if isinstance(x, jax.Array) else x,
                final_state)
            bad_cur = bad_cur[:n_local]
            keys = keys[:n_local]
        if prof_on:                   # stop beyond the last segment index
            jax.profiler.stop_trace()
            telem.emit("metric", "profile_capture", seg=len(plan) - 1,
                       action="stop")
        writer.barrier()              # all fetches + snapshots complete
        if n_procs > 1 and ckw is None:
            # checkpoint-free mesh run: no commit gather ever carried the
            # per-rank telemetry deltas, so without this the run records
            # per-rank streams but no committer skew marks (the ROADMAP
            # observability gap).  One end-of-run gather closes it: every
            # multi-process run reports at least a final `rank_skew`.
            from ..obs.events import record_rank_skew
            with telem.span("barrier_wait", what="end-skew-gather"):
                parts = coord.all_gather({"telemetry": telem.mark_delta()},
                                         tag="end-skew")
            if coord.is_coordinator:
                record_rank_skew(telem, "end",
                                 [p.get("telemetry") for p in parts])
        telem.emit("run", "end", samples_done=base_samples + done)
        _merge_segs()
        recs = host_segs[0]
    finally:
        try:
            if prof_on:               # unwound inside the capture window
                # (preemption, coordination failure, ...): the profiler
                # must not stay active — it would poison the next
                # start_trace in this process
                jax.profiler.stop_trace()
                telem.emit("metric", "profile_capture", action="abort")
        except NameError:
            pass                      # failed before the loop started
        except Exception:             # noqa: BLE001 — cleanup must not
            pass                      # mask the original unwind
        try:
            writer.shutdown()         # drain in-flight writes even on error
        except NameError:
            pass                      # failed before the writer existed
        telem.flush()                 # whatever the writer did not drain
        if restore_handlers:
            import signal as _signal
            for s, h in restore_handlers:
                _signal.signal(s, h)
    t2 = time.perf_counter()
    ck_io = (ckw.io if ckw is not None else
             {"bytes": 0, "snapshot_bytes": [], "shards_written": 0})
    tel_tot = telem.totals()
    io_stats = {"pipeline": bool(pipeline), "segments": len(plan),
                "checkpoints": ckw.n_writes if ckw is not None else 0,
                "checkpoint_layout": checkpoint_layout if ck_every else None,
                "max_queue_depth": writer.max_depth_seen,
                "writer_busy_s": writer.busy_s,
                "bytes_written": ck_io["bytes"],
                "snapshot_bytes": list(ck_io["snapshot_bytes"]),
                "shards_written": ck_io["shards_written"],
                # coordination observability: time this process spent
                # waiting on cross-process barriers/gathers, and time the
                # committer spent writing manifest commits (both 0.0 for a
                # run without checkpointing).  io_stats is a
                # backward-compatible VIEW over the run telemetry: the
                # time fields are THE span aggregates (CheckpointWriter
                # times its stages through telem.span; there is no second
                # accounting to drift), the event stream carries the rest
                "barrier_wait_s": tel_tot.get("barrier_wait",
                                              {}).get("total_s", 0.0),
                "manifest_commit_s": tel_tot.get("manifest_commit",
                                                 {}).get("total_s", 0.0),
                "process_count": n_procs, "process_index": proc,
                "telemetry_events": int(telem.n_events)}

    post = Posterior(hM, spec, recs, samples=samples,
                     transient=_transient_base + int(transient), thin=thin)
    post.timing = {"setup_s": t1 - t0, "run_s": t2 - t1}
    post.io_stats = io_stats
    post.telemetry = telem.summary(wall_s=t2 - t1)
    post.updater_profile = prof_upd

    # divergence observability + containment: report each poisoned chain's
    # first non-finite sweep and exclude it from pooled summaries (a user
    # running chains overnight must not get silent garbage averaged in)
    first_bad = np.asarray(bad_cur)
    post.set_chain_health(first_bad)
    for c in np.nonzero(first_bad >= 0)[0]:
        import warnings
        warnings.warn(
            f"chain {c} diverged: non-finite state first seen at sweep "
            f"{int(first_bad[c])} (of {total_it}); its draws are excluded "
            f"from pooled summaries (see Posterior.chain_health)",
            RuntimeWarning, stacklevel=2)

    # factor-cap saturation counts per chain (warned about below, after a
    # possible retry_diverged splice replaces chains and their counts)
    nf_sat_counts = {r: np.asarray(final_state.levels[r].nf_sat).reshape(-1)
                     for r in range(spec.nr)}

    # opt-in restart: re-run just the poisoned chains with a fresh key
    # stream and splice the replacements in (chains are independent, so the
    # spliced posterior targets the same distribution)
    if retry_diverged > 0 and n_procs == 1 and (first_bad >= 0).any():
        bad = np.nonzero(first_bad >= 0)[0]
        # the mesh is forwarded when the retry chain count still lays out
        # evenly over its chain axis (so an HBM-bound species-sharded model
        # can fit during the retry too)
        sub_mesh = mesh
        if mesh is not None and len(bad) % int(mesh.shape[chain_axis]) != 0:
            sub_mesh = None
        # the replacement carry state is needed whenever the caller asked for
        # it OR a final checkpoint must be re-written: snapshotting the
        # pre-splice state would hand a later resume_run(extra_samples=...)
        # the NaN-poisoned carry of the very chain the retry just replaced
        want_state = return_state or bool(ck_every)
        # warm restart (append layout): the newest manifest at which every
        # diverged chain was still healthy carries a usable mid-run carry —
        # keep those chains' healthy draws up to that snapshot and re-run
        # only the remainder with a FRESH key stream (the carried key would
        # replay the exact same path into the same divergence), instead of
        # repeating the whole burn-in from scratch
        if ck_every and append_layout:
            with telem.span("warm_restart_find"):
                warm = _find_warm_restart(ck_dir, hM, bad, base_samples,
                                          samples)
        else:
            warm = None
        if warm is not None:
            warm_state, warm_s0, warm_t_done = warm
            sub_init = jax.tree.map(
                lambda x: jnp.asarray(np.asarray(x)[bad]), warm_state)
            rem_t = (max(0, (it0 + int(transient)) - int(warm_t_done))
                     if warm_s0 == 0 and warm_t_done else 0)
            sub = sample_mcmc(hM, samples=samples - warm_s0,
                              transient=rem_t, thin=thin,
                              n_chains=len(bad),
                              seed=int(rng.integers(2**31 - 1)),
                              adapt_nf=[int(a) for a in adapt_nf],
                              updater=updater, nf_cap=nf_cap, dtype=dtype,
                              data_par=data_par, align_post=False,
                              verbose=verbose, mesh=sub_mesh,
                              chain_axis=chain_axis,
                              species_axis=species_axis,
                              site_axis=site_axis,
                              shard_sweep=shard_sweep,
                              precision_policy=(policy.to_meta()
                                                if policy is not None
                                                else None),
                              local_rng=(local_rng and sub_mesh is not None),
                              init_state=sub_init,
                              rng_impl=rng_impl, record_dtype=record_dtype,
                              retry_diverged=retry_diverged - 1,
                              record=record, return_state=want_state)
            splice_from = int(warm_s0)
        else:
            # cold restart: re-initialise from scratch — without a healthy
            # snapshot a poisoned carry would diverge again immediately.
            # Burn-in covers the original chain's total progress
            # (it0 + transient); adapt_nf is re-derived from the caller's
            # argument against that burn-in (a resumed run's resolved
            # (0,...) must not skip adaptation in a from-scratch restart)
            sub = sample_mcmc(hM, samples=samples,
                              transient=int(transient) + it0, thin=thin,
                              n_chains=len(bad),
                              seed=int(rng.integers(2**31 - 1)),
                              init_par=init_par, adapt_nf=adapt_nf_arg,
                              updater=updater, nf_cap=nf_cap, dtype=dtype,
                              data_par=data_par, align_post=False,
                              verbose=verbose,
                              mesh=sub_mesh, chain_axis=chain_axis,
                              species_axis=species_axis,
                              site_axis=site_axis,
                              shard_sweep=shard_sweep,
                              precision_policy=(policy.to_meta()
                                                if policy is not None
                                                else None),
                              local_rng=(local_rng and sub_mesh is not None),
                              rng_impl=rng_impl, record_dtype=record_dtype,
                              retry_diverged=retry_diverged - 1,
                              record=record, return_state=want_state)
            splice_from = 0
        if want_state:
            sub, sub_state = sub

            def _splice(a, b):
                a = np.asarray(a).copy()
                a[bad] = np.asarray(b)
                return jnp.asarray(a)
            final_state = jax.tree.map(_splice, final_state, sub_state)
        for k in post.arrays:
            a = post.arrays[k]
            if not a.flags.writeable:        # np.asarray views of jax buffers
                a = a.copy()
            a[bad, splice_from:] = sub.arrays[k]
            post.arrays[k] = a
        first_bad = first_bad.copy()
        first_bad[bad] = sub.chain_health["first_bad_it"]
        post.set_chain_health(first_bad)
        # surface the restart in the posterior's metadata (operations
        # audit: a spliced chain is a different realisation than the one
        # the seed alone would have produced)
        post.retry_info = {
            "retried_chains": tuple(int(c) for c in bad),
            "healthy_after_retry": tuple(
                bool(b < 0) for b in
                np.asarray(sub.chain_health["first_bad_it"])),
            "warm_start_samples": splice_from if warm is not None else None,
        }
        for r in range(spec.nr):          # replacement chains' counts
            nf_sat_counts[r] = nf_sat_counts[r].copy()
            nf_sat_counts[r][bad] = sub.nf_saturation[r]
        if ck_every:
            # the splice changed recorded draws AND the carry state AFTER
            # the final snapshot was written inside the segment loop —
            # re-write it so resume_run of the completed run returns the
            # spliced (healthy) posterior and any extension continues from
            # the replacement chains' healthy carry, not the poisoned one
            post.nf_saturation = nf_sat_counts
            if append_layout:
                ckw.rewrite_spliced(
                    splice_from, int(samples), final_state,
                    jnp.array(jax.random.key_data(keys)), first_bad, post,
                    dict(_run_meta(int(samples)),
                         retry_info=post.retry_info))
            else:
                ckw.rewrite_rotating(int(samples), final_state, keys,
                                     first_bad, post, _run_meta(int(samples)))
            # the rewrite ran after io_stats was snapshotted — refresh the
            # byte accounting so the repair shard / re-written slot counts
            post.io_stats.update(
                bytes_written=ckw.io["bytes"],
                snapshot_bytes=list(ckw.io["snapshot_bytes"]),
                shards_written=ckw.io["shards_written"],
                telemetry_events=int(telem.n_events))
            post.telemetry = telem.summary(wall_s=t2 - t1)
            telem.flush()             # the splice spans landed post-barrier

    # coordinated multi-process retry (closes the carried ROADMAP gap): the
    # final commit already gathered every rank's divergence health; close
    # the loop by agreeing on the diverged chain set, unwinding every rank
    # to the SAME last-healthy manifest, warm-restarting the diverged
    # chains on their owning rank(s), and re-committing the repair at that
    # shared boundary — exactly the single-process splice, coordinated.
    # The gate is rank-invariant (retry_diverged / n_procs / ck_every are
    # identical everywhere), so the collective sequence stays SPMD even
    # when only one rank diverged — or none did (the health gather still
    # runs; the repair protocol only engages on an agreed non-empty set).
    if retry_diverged > 0 and n_procs > 1 and ck_every:
        with telem.span("barrier_wait", what="retry-health"):
            parts = coord.all_gather(
                {"first_bad": [int(x) for x in first_bad]},
                tag="retry-health")
        fb_global = np.asarray([x for p in parts for x in p["first_bad"]])
        bad_g = np.nonzero(fb_global >= 0)[0]
        if bad_g.size:
            # the shared splice boundary: the newest manifest at which
            # EVERY diverged chain (global index) was still healthy —
            # found by a deterministic scan of the shared directory, so
            # all ranks agree without an extra broadcast
            with telem.span("warm_restart_find"):
                warm = _find_warm_restart(ck_dir, hM, bad_g, base_samples,
                                          samples)
            if warm is not None:
                warm_state, warm_s0, warm_t_done = warm
                splice_from = int(warm_s0)
                rem_t = (max(0, (it0 + int(transient)) - int(warm_t_done))
                         if warm_s0 == 0 and warm_t_done else 0)
            else:
                warm_state, splice_from = None, 0
                rem_t = int(transient) + it0
            # per-rank retry seeds drawn from the shared rng stream (its
            # state is identical on every rank), one slot per rank so two
            # owning ranks never reuse a seed
            retry_seeds = rng.integers(0, 2**31 - 1, size=n_procs)
            own_bad_g = [int(c) for c in bad_g
                         if chains_lo <= int(c) < chains_lo + n_local]
            rows = np.asarray([c - chains_lo for c in own_bad_g],
                              dtype=np.int64)
            healthy_after = {}
            if own_bad_g:
                from ..utils.coordination import SingleProcessCoordinator
                common = dict(
                    thin=thin, n_chains=len(own_bad_g),
                    seed=int(retry_seeds[proc]), updater=updater,
                    nf_cap=nf_cap, dtype=dtype, data_par=data_par,
                    align_post=False, verbose=verbose, rng_impl=rng_impl,
                    record_dtype=record_dtype,
                    retry_diverged=retry_diverged - 1, record=record,
                    # the repair restart runs replicated single-process:
                    # keep the policy'd numerics, drop the shard-local RNG
                    precision_policy=(policy.to_meta() if policy is not None
                                      else None),
                    coordinator=SingleProcessCoordinator(),
                    return_state=True)
                if warm_state is not None:
                    # slice this rank's diverged chains (global indices)
                    # out of the manifest's stitched GLOBAL carry
                    sub_init = jax.tree.map(
                        lambda x: jnp.asarray(np.asarray(x)[own_bad_g]),
                        warm_state)
                    sub, sub_state = sample_mcmc(
                        hM, samples=samples - splice_from, transient=rem_t,
                        adapt_nf=[int(a) for a in adapt_nf],
                        init_state=sub_init, **common)
                else:
                    # no healthy snapshot survives rotation: cold restart
                    # covering the original chains' full progress
                    sub, sub_state = sample_mcmc(
                        hM, samples=samples, transient=rem_t,
                        init_par=init_par, adapt_nf=adapt_nf_arg, **common)
                for k in post.arrays:
                    a = post.arrays[k]
                    if not a.flags.writeable:
                        a = a.copy()
                    a[rows, splice_from:] = sub.arrays[k]
                    post.arrays[k] = a
                sub_fb = np.asarray(sub.chain_health["first_bad_it"])
                first_bad = first_bad.copy()
                first_bad[rows] = sub_fb
                post.set_chain_health(first_bad)

                def _splice_rows(a, b):
                    a = np.asarray(a).copy()
                    a[rows] = np.asarray(b)
                    return jnp.asarray(a)
                final_state = jax.tree.map(_splice_rows, final_state,
                                           sub_state)
                for r in range(spec.nr):
                    nf_sat_counts[r] = nf_sat_counts[r].copy()
                    nf_sat_counts[r][rows] = sub.nf_saturation[r]
                healthy_after = {int(g): bool(b < 0)
                                 for g, b in zip(own_bad_g, sub_fb)}
            # Post-repair collectives run under a raised coordination
            # timeout: a healthy rank legitimately waits out its peers'
            # warm re-sample here, which can far exceed the per-commit
            # timeout the run was configured with (the re-run covers up
            # to the whole sampling window).
            with coord.timeout_override(_RETRY_REPAIR_TIMEOUT_S):
                # share the outcome so every rank records identical
                # retry_info (and the committer can put the global view
                # in the manifest)
                with telem.span("barrier_wait", what="retry-outcome"):
                    out_parts = coord.all_gather(
                        {"healthy": {str(k): v
                                     for k, v in healthy_after.items()}},
                        tag="retry-outcome")
                healthy_g = {}
                for p in out_parts:
                    healthy_g.update({int(k): bool(v)
                                      for k, v in p["healthy"].items()})
                retry_info = {
                    "retried_chains": tuple(int(c) for c in bad_g),
                    "healthy_after_retry": tuple(
                        healthy_g.get(int(c), False) for c in bad_g),
                    "warm_start_samples": (splice_from if warm is not None
                                           else None),
                }
                post.retry_info = retry_info
                post.nf_saturation = nf_sat_counts
                # the repair commit: owning ranks supersede their changed
                # tail with a repair shard; every rank re-saves its
                # chain-slice state; the committer overwrites the final
                # manifest over the repaired sequence — all at the same
                # shared boundary
                ckw.rewrite_spliced_multi(
                    splice_from, int(samples), final_state,
                    jnp.array(jax.random.key_data(keys)), first_bad, post,
                    dict(_run_meta(int(samples)), retry_info=retry_info),
                    changed=bool(own_bad_g))
            post.io_stats.update(
                bytes_written=ckw.io["bytes"],
                snapshot_bytes=list(ckw.io["snapshot_bytes"]),
                shards_written=ckw.io["shards_written"],
                telemetry_events=int(telem.n_events))
            post.telemetry = telem.summary(wall_s=t2 - t1)
            telem.flush()

    # factor-cap observability: warn when burn-in adaptation wanted to add
    # factors past the static nf_max cap — the residual associations may be
    # rank-starved and the user should consider a larger nf_cap (the
    # reference grows unbounded to nfMax=ns, updateNf.R:26)
    post.nf_saturation = nf_sat_counts
    for r in range(spec.nr):
        cnt = nf_sat_counts[r]
        if (cnt > 0).any():
            import warnings
            warnings.warn(
                f"random level '{spec.levels[r].name}': factor adaptation "
                f"hit the nf_max cap ({spec.levels[r].nf_max}) and wanted to "
                f"add more factors ({cnt.tolist()} blocked attempts per "
                "chain); residual associations may be rank-starved — raise "
                "nf_cap in sample_mcmc (or the level's nf_max prior) and "
                "refit", RuntimeWarning, stacklevel=2)

    if align_post and spec.nr > 0:
        from ..post.align import align_posterior
        for _ in range(5):
            if align_posterior(post) == 0:     # converged: pass was a no-op
                break
    if return_state:
        return post, final_state
    return post
