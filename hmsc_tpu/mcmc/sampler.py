"""Posterior sampling driver (reference ``R/sampleMcmc.R:68-380``).

TPU execution model (SURVEY.md §2.3 "Parallelism"):

- one jitted sweep per model config, ``lax.scan`` over iterations with
  strided sample recording (transient / thin handled inside the scan);
- independent chains are a leading batch axis via ``vmap``;
- multi-device: the chain axis (and optionally the species axis) is laid out
  over a ``jax.sharding.Mesh`` — XLA inserts the (trivial, gather-only)
  collectives; there is no inter-chain communication during sampling.

The reference's SOCK-cluster process fan-out collapses into this one
compiled program.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from ..model import Hmsc
from ..precompute import compute_data_parameters
from .structs import (DEFAULT_NF_CAP, build_model_data, build_spec, build_state)
from .sweep import effective_spec_data, make_sweep, record_sample
from . import updaters as U

__all__ = ["sample_mcmc"]


@functools.lru_cache(maxsize=64)
def _compiled_runner(spec, updater_items, adapt_nf, samples, transient, thin):
    """One jitted chain-vmapped sampling program per static config.

    Keyed on the hashable (spec, updater toggles, scan lengths) so repeated
    ``sample_mcmc`` calls with the same shapes reuse the compiled executable
    (XLA compilation is the dominant cost for small models)."""
    updater = dict(updater_items) if updater_items else None
    sweep = make_sweep(spec, updater, adapt_nf)

    def run_chain(data, state, key):
        key, k0 = jax.random.split(key)
        spec0, data0 = effective_spec_data(spec, data, state)
        state = U.update_z(spec0, data0, state, k0)  # reference inits Z via one updateZ pass

        def one_iter(carry, _):
            state, key = carry
            key, sub = jax.random.split(key)
            state = sweep(data, state, sub)
            return (state, key), None

        carry = (state, key)
        if transient > 0:
            carry, _ = jax.lax.scan(one_iter, carry, None, length=transient)

        def sample_step(carry, _):
            carry, _ = jax.lax.scan(one_iter, carry, None, length=thin)
            rec = record_sample(spec, data, carry[0])
            return carry, rec

        carry, recs = jax.lax.scan(sample_step, carry, None, length=samples)
        return recs, carry[0]

    return jax.jit(jax.vmap(run_chain, in_axes=(None, 0, 0)))


def sample_mcmc(hM: Hmsc, samples: int, transient: int = 0, thin: int = 1,
                n_chains: int = 1, seed: int | None = None, init_par=None,
                adapt_nf=None, updater: dict | None = None,
                nf_cap: int = DEFAULT_NF_CAP, dtype=jnp.float32,
                data_par=None, from_prior: bool = False,
                align_post: bool = True, mesh=None, chain_axis: str = "chains",
                return_state: bool = False):
    """Run the blocked Gibbs sampler; returns a :class:`~hmsc_tpu.post.Posterior`.

    Arguments mirror the reference's ``sampleMcmc`` (samples/transient/thin/
    nChains/initPar/adaptNf/updater/dataParList/fromPrior/alignPost); the
    process-parallel ``nParallel`` is replaced by device parallelism via
    ``mesh``.
    """
    from ..post.posterior import Posterior

    if adapt_nf is None:
        adapt_nf = tuple(transient for _ in range(hM.nr))
    else:
        adapt_nf = tuple(int(a) for a in np.broadcast_to(adapt_nf, (hM.nr,)))
    if any(a > transient for a in adapt_nf):
        raise ValueError("transient parameter should be no less than any element of adaptNf parameter")

    spec = build_spec(hM, nf_cap)
    if data_par is None:
        data_par = compute_data_parameters(hM)
    data = build_model_data(hM, data_par, spec, dtype=dtype)

    rng = np.random.default_rng(seed)
    chain_seeds = rng.integers(0, 2**31 - 1, size=n_chains)

    if from_prior:
        from .prior import sample_prior_chains
        post = sample_prior_chains(hM, spec, data_par, samples, n_chains, rng)
        return Posterior(hM, spec, post, samples=samples, transient=transient,
                         thin=thin)

    states = [build_state(hM, spec, int(s), init_par, dtype=dtype)
              for s in chain_seeds]
    state0 = jax.tree.map(lambda *xs: jnp.stack(xs), *states)
    keys = jax.vmap(jax.random.PRNGKey)(jnp.asarray(chain_seeds))

    # structural gates for the opt-in collapsed updaters (reference
    # auto-gating, sampleMcmc.R:123-152; see updaters_marginal)
    if updater and (updater.get("Gamma2") is True
                    or updater.get("GammaEta") is True):
        from .updaters_marginal import gamma_eta_gates
        gates = gamma_eta_gates(spec, mGamma=hM.mGamma)
        updater = dict(updater)
        for name in ("Gamma2", "GammaEta"):
            if updater.get(name) is True and gates[name]:
                print(f"Setting updater${name}=FALSE: {gates[name]}")
                updater[name] = False

    updater_items = (tuple(sorted(updater.items())) if updater else None)
    fn = _compiled_runner(spec, updater_items, adapt_nf,
                          int(samples), int(transient), int(thin))
    if mesh is not None:
        # shard the chain batch axis over the mesh; everything else replicates
        from jax.sharding import NamedSharding, PartitionSpec as P
        sh = NamedSharding(mesh, P(chain_axis))
        state0 = jax.tree.map(lambda x: jax.device_put(x, sh), state0)
        keys = jax.device_put(keys, sh)

    recs, final_state = fn(data, state0, keys)
    recs = jax.tree.map(np.asarray, recs)        # (chains, samples, ...)

    post = Posterior(hM, spec, recs, samples=samples, transient=transient,
                     thin=thin)
    if align_post and spec.nr > 0:
        from ..post.align import align_posterior
        for _ in range(5):
            align_posterior(post)
    if return_state:
        return post, final_state
    return post
