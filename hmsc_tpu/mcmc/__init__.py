from .structs import (GibbsState, LevelSpec, LevelState, ModelData, ModelSpec,
                      build_model_data, build_state, LevelData,
                      state_nbytes)
from .sampler import sample_mcmc
from .precision import PRECISION_AGREEMENT_TOL, PrecisionPolicy

__all__ = ["GibbsState", "LevelSpec", "LevelState", "ModelData", "ModelSpec",
           "LevelData", "build_model_data", "build_state", "state_nbytes",
           "sample_mcmc", "PrecisionPolicy", "PRECISION_AGREEMENT_TOL"]
