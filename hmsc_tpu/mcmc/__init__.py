from .structs import (GibbsState, LevelSpec, LevelState, ModelData, ModelSpec,
                      build_model_data, build_state, LevelData, LevelTenant,
                      TenantMasks, state_nbytes)
from .sampler import sample_mcmc
from .multitenant import (TENANT_PAD_AGREEMENT_TOL, sample_mcmc_batched)
from .precision import PRECISION_AGREEMENT_TOL, PrecisionPolicy

__all__ = ["GibbsState", "LevelSpec", "LevelState", "ModelData", "ModelSpec",
           "LevelData", "LevelTenant", "TenantMasks", "build_model_data",
           "build_state", "state_nbytes", "sample_mcmc",
           "sample_mcmc_batched", "TENANT_PAD_AGREEMENT_TOL",
           "PrecisionPolicy", "PRECISION_AGREEMENT_TOL"]
