"""hmsc_tpu — a TPU-native framework for Hierarchical Modelling of Species
Communities (Bayesian joint species distribution models).

A ground-up JAX/XLA re-architecture of the capability set of the HMSC R
package (reference surveyed in SURVEY.md): blocked Gibbs sampling of latent
Gaussian JSDMs with traits, phylogeny, adaptive latent factors, spatial random
levels (Full GP / GPP / NNGP), mixed observation models, variable selection
and reduced-rank regression — with chains vmapped over a device mesh and all
hot updates as batched, jit-compiled array programs.
"""

from .model import Hmsc, XSelect, set_priors
from .random_level import HmscRandomLevel, set_priors_random_level
from .precompute import (compute_data_parameters, compute_initial_parameters,
                         construct_knots)
from .mcmc.sampler import sample_mcmc, grow_carry_state
from .mcmc.multitenant import sample_mcmc_batched
from .refit import update_run, append_data, load_epoch_posterior
from .post import (Posterior, pool_mcmc_chains, compute_associations,
                   convert_to_coda_object, effective_size, gelman_rhat,
                   align_posterior, evaluate_model_fit, compute_waic,
                   compute_variance_partitioning)
from .predict import (predict, predict_latent_factor, compute_predicted_values,
                      create_partition, construct_gradient, prepare_gradient)
from .utils.checkpoint import (save_checkpoint, load_checkpoint,
                               load_checkpoint_full,
                               load_manifest_checkpoint, gc_checkpoints,
                               concat_posteriors,
                               resume_run, CheckpointError,
                               CheckpointCorruptError,
                               CheckpointSpecMismatchError, PreemptedRun)
from .utils.mesh import make_mesh
from .serve import (ServingEngine, ServingArtifact, compact_posterior,
                    load_artifact)
from .obs import (RunTelemetry, RunningDiagnostics, get_logger, rhat_ess)
from .utils.phylo import parse_newick, phylo_corr, vcv_from_newick
from .plots import (plot_beta, plot_gamma, plot_gradient,
                    plot_variance_partitioning, bi_plot)

# reference-style camelCase aliases
sampleMcmc = sample_mcmc
sampleMcmcBatched = sample_mcmc_batched
setPriors = set_priors
computeDataParameters = compute_data_parameters
computeInitialParameters = compute_initial_parameters
constructKnots = construct_knots
poolMcmcChains = pool_mcmc_chains
computeAssociations = compute_associations
convertToCodaObject = convert_to_coda_object
alignPosterior = align_posterior
evaluateModelFit = evaluate_model_fit
computeWAIC = compute_waic
computeVariancePartitioning = compute_variance_partitioning
predictLatentFactor = predict_latent_factor
computePredictedValues = compute_predicted_values
createPartition = create_partition
constructGradient = construct_gradient
prepareGradient = prepare_gradient
plotBeta = plot_beta
plotGamma = plot_gamma
plotGradient = plot_gradient
plotVariancePartitioning = plot_variance_partitioning
biPlot = bi_plot

# the single source of truth: pyproject.toml declares its version dynamic
# and reads this literal (setuptools resolves the attr by AST, no import)
__version__ = "0.5.0"

__all__ = [
    "Hmsc", "HmscRandomLevel", "XSelect", "set_priors",
    "set_priors_random_level", "compute_data_parameters",
    "compute_initial_parameters", "construct_knots", "sample_mcmc",
    "Posterior", "pool_mcmc_chains", "compute_associations",
    "convert_to_coda_object", "effective_size", "gelman_rhat",
    "align_posterior",
    "evaluate_model_fit", "compute_waic", "compute_variance_partitioning",
    "predict", "predict_latent_factor", "compute_predicted_values",
    "create_partition", "construct_gradient", "prepare_gradient",
    "save_checkpoint", "load_checkpoint", "load_checkpoint_full",
    "load_manifest_checkpoint", "gc_checkpoints",
    "concat_posteriors", "resume_run", "CheckpointError",
    "CheckpointCorruptError", "CheckpointSpecMismatchError", "PreemptedRun",
    "make_mesh",
    "RunTelemetry", "RunningDiagnostics", "get_logger", "rhat_ess",
    "parse_newick", "phylo_corr", "vcv_from_newick",
    "plot_beta", "plot_gamma", "plot_gradient",
    "plot_variance_partitioning", "bi_plot",
    "sampleMcmc", "setPriors", "computeDataParameters",
    "computeInitialParameters", "constructKnots", "poolMcmcChains",
    "computeAssociations", "convertToCodaObject", "alignPosterior",
    "evaluateModelFit", "computeWAIC", "computeVariancePartitioning",
    "predictLatentFactor", "computePredictedValues", "createPartition",
    "constructGradient", "prepareGradient", "plotBeta", "plotGamma",
    "plotGradient", "plotVariancePartitioning", "biPlot",
]
