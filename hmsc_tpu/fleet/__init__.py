"""Elastic fleet supervision for preemptible capacity.

``python -m hmsc_tpu fleet <config.json>`` runs a
:class:`~hmsc_tpu.fleet.supervisor.FleetSupervisor`: R worker ranks under
a ``FileCoordinator``, heartbeat liveness detection, exponential-backoff
restarts under per-rank budgets, and shrink/grow degradation at committed
manifest boundaries — zero committed draws lost, ever.  See the
supervisor module docstring and README "Elastic fleet runs".
"""

from .config import FleetConfig
from .supervisor import FleetSupervisor, fleet_events_path

__all__ = ["FleetConfig", "FleetSupervisor", "fleet_events_path"]
