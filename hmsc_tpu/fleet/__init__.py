"""Elastic fleet supervision for preemptible capacity — and replicated
posterior serving.

``python -m hmsc_tpu fleet <config.json>`` runs a
:class:`~hmsc_tpu.fleet.supervisor.FleetSupervisor`: R worker ranks under
a ``FileCoordinator``, heartbeat liveness detection, exponential-backoff
restarts under per-rank budgets, and shrink/grow degradation at committed
manifest boundaries — zero committed draws lost, ever.  See the
supervisor module docstring and README "Elastic fleet runs".

``python -m hmsc_tpu serve --fleet <config.json>`` runs a
:class:`~hmsc_tpu.fleet.serving.ServingFleet`: the same supervision
machinery (heartbeats, exit-code taxonomy, backoff budgets) promoted to
the query side — N ``ServingEngine`` replica processes behind one
least-loaded front end, with coordinated generation-checked epoch flips.
See README "Serving at scale".
"""

from .config import FleetConfig
from .serving import ServeFleetConfig, ServingFleet, serve_fleet_main
from .supervisor import FleetSupervisor, fleet_events_path

__all__ = ["FleetConfig", "FleetSupervisor", "fleet_events_path",
           "ServeFleetConfig", "ServingFleet", "serve_fleet_main"]
