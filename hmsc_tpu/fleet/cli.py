"""``python -m hmsc_tpu fleet <config.json>`` — run a supervised fleet.

Spawns the configured number of worker ranks, watches exit codes and
heartbeats, restarts failures with exponential backoff, shrinks/grows the
fleet at committed manifest boundaries, and prints one JSON summary line.
See README "Elastic fleet runs" for the config schema
(:class:`~hmsc_tpu.fleet.config.FleetConfig`) and the degradation policy.

Exit codes follow :mod:`hmsc_tpu.exit_codes`: 0 when the fleet completed
with a checksum-valid final manifest and zero committed draws lost; 77
when the run ended diverged; 78 when no usable checkpoint remained; 1 for
any other supervision failure.
"""

from __future__ import annotations

import argparse
import json

from ..exit_codes import EXIT_CKPT_CORRUPT, EXIT_DIVERGED, EXIT_FAILURE


def fleet_main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hmsc_tpu fleet",
        description="elastic fleet supervisor: spawn R worker ranks, "
                    "restart failures with backoff, shrink/grow at "
                    "committed manifest boundaries; with --jobs (or a "
                    "config jobs_dir) run the JOB-QUEUE mode instead — bin "
                    "job files by padded shape bucket and dispatch each "
                    "bucket as one supervised batched fitting job")
    ap.add_argument("config", nargs="?", default=None,
                    help="JSON fleet config (FleetConfig schema); optional "
                         "in --jobs mode when --ckpt-dir/--work-dir are "
                         "given")
    ap.add_argument("--jobs", default=None,
                    help="job-queue mode: directory of *.json job files "
                         "(see hmsc_tpu.fleet.jobs for the schema)")
    ap.add_argument("--nprocs", type=int, default=None,
                    help="override the config's initial fleet size")
    ap.add_argument("--ckpt-dir", default=None,
                    help="override the config's checkpoint directory")
    ap.add_argument("--work-dir", default=None,
                    help="override the config's scratch directory "
                         "(coordination sentinels, heartbeats, worker logs)")
    ap.add_argument("--chaos-seed", type=int, default=None,
                    help="arm a seeded Poisson kill schedule against the "
                         "fleet (chaos drill; see --chaos-rate/horizon)")
    ap.add_argument("--chaos-rate", type=float, default=0.05,
                    help="Poisson kill rate per second (with --chaos-seed)")
    ap.add_argument("--chaos-horizon", type=float, default=120.0,
                    help="kill-schedule horizon in seconds "
                         "(with --chaos-seed)")
    args = ap.parse_args(argv)

    from .config import FleetConfig
    from .supervisor import FleetSupervisor

    if args.config is not None:
        cfg = FleetConfig.from_json(args.config, nprocs=args.nprocs,
                                    ckpt_dir=args.ckpt_dir,
                                    work_dir=args.work_dir,
                                    jobs_dir=args.jobs)
    elif args.jobs is not None:
        if args.ckpt_dir is None or args.work_dir is None:
            ap.error("--jobs without a config file requires --ckpt-dir "
                     "and --work-dir")
        cfg = FleetConfig(ckpt_dir=args.ckpt_dir, work_dir=args.work_dir,
                          nprocs=1, jobs_dir=args.jobs)
    else:
        ap.error("a config file (or --jobs with --ckpt-dir/--work-dir) "
                 "is required")

    if cfg.jobs_dir is not None:
        if args.chaos_seed is not None:
            # the Poisson rank-kill schedule targets worker ranks; wiring
            # it to bucket jobs is future work — refuse rather than let an
            # operator believe a chaos drill ran (JobQueue.run's
            # chaos_kill_at hook covers the drill in tests)
            ap.error("--chaos-seed is not supported in job-queue mode")
        from .jobs import JobQueue
        summary = JobQueue(cfg).run()
        # the bulk CV prediction matrices stay in the returned summary for
        # API callers; the CLI's one-line JSON keeps the verdicts only
        print(json.dumps({k: v for k, v in summary.items()
                          if k != "scenario_preds"}))
        if summary["ok"]:
            return 0
        # same failure-class taxonomy as the rank fleet below: a queue
        # whose only failures are surfaced divergences exits 77
        return {"diverged": EXIT_DIVERGED}.get(summary["status"],
                                               EXIT_FAILURE)

    chaos = None
    if args.chaos_seed is not None:
        from ..testing.chaos import poisson_schedule
        chaos = poisson_schedule(args.chaos_seed, args.chaos_rate,
                                 args.chaos_horizon, cfg.nprocs)
    summary = FleetSupervisor(cfg, chaos=chaos).run()
    print(json.dumps(summary))
    if summary["ok"]:
        return 0
    return {"diverged": EXIT_DIVERGED,
            "checkpoint-corrupt": EXIT_CKPT_CORRUPT}.get(
        summary["status"], EXIT_FAILURE)


if __name__ == "__main__":
    raise SystemExit(fleet_main())
