"""Fleet supervisor configuration.

A fleet run is described by one JSON document (the ``python -m hmsc_tpu
fleet <config.json>`` argument) mapping 1:1 onto :class:`FleetConfig`.
Everything has a usable default except the two directories, so a minimal
config is::

    {"ckpt_dir": "/data/run-1/ck", "work_dir": "/data/run-1/fleet",
     "nprocs": 4,
     "run_kw": {"samples": 200, "transient": 50, "n_chains": 4,
                "checkpoint_every": 25, "seed": 7}}

``run_kw``/``model_kw`` are passed verbatim to the worker
(:mod:`hmsc_tpu.testing.multiproc`), i.e. to ``sample_mcmc`` /
``build_worker_model`` — the supervisor itself never interprets them
beyond ``samples`` and ``n_chains``.
"""

from __future__ import annotations

import dataclasses
import json
import os

__all__ = ["FleetConfig"]


@dataclasses.dataclass
class FleetConfig:
    """Everything the supervisor needs to run and heal one fleet.

    Degradation policy: when any rank slot exhausts ``restart_budget``
    consecutive failures, the fleet shrinks to the next process count on
    the :meth:`ladder` (divisors of ``n_chains``, so resume re-shards the
    chains evenly) at the next restart — resume always continues from the
    last committed manifest, so no committed draw is ever at risk.  After
    ``grow_after_attempts`` attempts at reduced size the capacity is
    considered recovered and the fleet grows one ladder step back, with
    the re-added slots' budgets refreshed."""

    ckpt_dir: str
    work_dir: str
    nprocs: int = 2
    model_kw: dict = dataclasses.field(default_factory=dict)
    run_kw: dict = dataclasses.field(default_factory=dict)
    # liveness
    heartbeat_interval_s: float = 0.25
    heartbeat_timeout_s: float = 20.0
    startup_grace_s: float = 240.0       # import + first compile headroom
    # restart policy
    restart_budget: int = 3
    backoff_base_s: float = 0.25
    backoff_factor: float = 2.0
    backoff_max_s: float = 30.0
    # degradation policy
    min_procs: int = 1
    grow_after_attempts: int = 2
    max_attempts: int = 16
    # spawn plumbing
    coord_timeout_s: float = 60.0
    wall_timeout_s: float = 600.0        # per attempt
    poll_s: float = 0.1
    pin_cpus: bool = False
    # job-queue mode (fleet --jobs / jobs_dir set): bin the directory's job
    # files by padded-shape bucket and dispatch each bucket as ONE
    # supervised batched fitting job (hmsc_tpu.fleet.jobs); run_kw then
    # feeds sample_mcmc_batched and nprocs/ladder are ignored
    jobs_dir: str | None = None
    bucket_rounding: dict | None = None
    # group every bucket of a queue run into ONE worker process (results
    # still land per bucket, and a restart re-dispatches only the buckets
    # without a result) — amortizes interpreter/JAX start-up across a
    # scenario sweep's buckets instead of paying it once per bucket
    group_buckets: bool = False

    def __post_init__(self):
        self.run_kw = dict(self.run_kw or {})
        self.model_kw = dict(self.model_kw or {})
        self.run_kw.setdefault("samples", 8)
        self.run_kw.setdefault("n_chains", max(1, int(self.nprocs)))
        self.run_kw.setdefault("checkpoint_every",
                               max(1, int(self.run_kw["samples"]) // 4))
        if int(self.nprocs) < 1 or int(self.min_procs) < 1:
            raise ValueError("nprocs and min_procs must be >= 1")
        if int(self.min_procs) > int(self.nprocs):
            raise ValueError(f"min_procs ({self.min_procs}) exceeds nprocs "
                             f"({self.nprocs})")
        if int(self.restart_budget) < 1:
            raise ValueError("restart_budget must be >= 1")
        if not self.ladder():
            raise ValueError(
                f"n_chains={self.n_chains} has no divisor between "
                f"min_procs={self.min_procs} and nprocs={self.nprocs}; "
                "chains must shard evenly over every fleet size")

    @property
    def n_chains(self) -> int:
        return int(self.run_kw["n_chains"])

    @property
    def samples(self) -> int:
        return int(self.run_kw["samples"])

    def ladder(self) -> list:
        """Fleet sizes the degradation policy may run at, descending —
        every divisor of ``n_chains`` in ``[min_procs, nprocs]`` (resume
        re-shards chains across process counts, but only even shards)."""
        return [r for r in range(int(self.nprocs), int(self.min_procs) - 1,
                                 -1) if self.n_chains % r == 0]

    @classmethod
    def from_json(cls, path: str, **overrides) -> "FleetConfig":
        with open(os.fspath(path)) as f:
            doc = json.load(f)
        if not isinstance(doc, dict):
            raise ValueError(f"{path}: fleet config must be a JSON object")
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(doc) - known)
        if unknown:
            raise ValueError(f"{path}: unknown fleet config key(s) "
                             f"{unknown}; valid keys: {sorted(known)}")
        doc.update({k: v for k, v in overrides.items() if v is not None})
        return cls(**doc)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)
