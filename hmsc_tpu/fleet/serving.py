"""Replicated posterior serving fleet: N engine replicas, one front end.

``python -m hmsc_tpu serve --fleet <config.json>`` promotes the fleet
supervisor's machinery to the QUERY side: it spawns ``replicas`` ordinary
``python -m hmsc_tpu serve`` processes (each its own
:class:`~hmsc_tpu.serve.engine.ServingEngine`, optionally draw-sharded
over its local devices) and puts one stdlib front end in front of them:

- **Dispatch** is least-loaded with round-robin tiebreak: every proxied
  query picks the live replica with the fewest in-flight requests.  A
  replica that dies mid-query answers with a connection error, and the
  front end transparently retries the query on another live replica — a
  chaos-killed replica drops ZERO queries.
- **Liveness** rides the existing machinery: each replica beats a
  :class:`~hmsc_tpu.utils.coordination.HeartbeatWriter` file (whose
  payload also carries the bound port — how a ``--port 0`` replica is
  discovered), exits are classified by the
  :mod:`hmsc_tpu.exit_codes` taxonomy, and a dead or heartbeat-silent
  replica is restarted with exponential backoff under a per-slot budget
  (exhausted slots leave the rotation; the fleet serves degraded).
- **Drain before kill**: a planned stop takes the replica out of the
  rotation first, waits for its in-flight queries to finish (bounded by
  ``drain_timeout_s``), then SIGTERMs it — the replica's own shutdown
  path flushes telemetry exactly like single-engine ``serve``.
- **Fleet-wide epoch flips** (``POST /flip`` on the front end): a
  rolling, generation-checked ``reload()`` on every replica — each
  replica's flip response must advance ITS generation by exactly one —
  and the flip is acknowledged only when every rotation member reports
  the target epoch from ``/healthz``.  A replica chaos-killed mid-flip
  is restarted by the watcher; the restarted process re-resolves the
  source and stages the newest committed epoch, so the coordinator just
  waits for it to report the target.  In-flight queries are never
  dropped and never mix generations: every response is computed against
  exactly one staged generation (engine contract) and is tagged with it.

Every decision is a ``kind="fleet"`` event in the work dir's
``fleet-events.jsonl`` (``report`` renders the serving-fleet timeline
with per-replica qps and queue-wait skew).
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import threading
import time

from ..exit_codes import EXIT_OK, describe
from .supervisor import fleet_events_path, log_tail

__all__ = ["ServeFleetConfig", "ServingFleet", "serve_fleet_main"]


@dataclasses.dataclass
class ServeFleetConfig:
    """Serving-fleet configuration (``serve --fleet config.json``).

    ``source`` is what each replica serves (a run directory or compacted
    artifact — exactly the single-engine ``serve`` positional);
    ``work_dir`` holds heartbeats, per-replica logs, and the fleet event
    stream.  Engine knobs (``buckets``/``coalesce_ms``/``draw_thin``/
    ``draw_shards``/``no_warmup``) are passed through to every replica.
    Supervision knobs mirror :class:`~hmsc_tpu.fleet.config.FleetConfig`:
    heartbeat cadence/timeout, per-slot restart budgets, exponential
    backoff ``min(base * factor**(fails-1), max)``.
    """

    source: str
    work_dir: str
    replicas: int = 3
    host: str = "127.0.0.1"
    port: int = 8080
    draw_shards: int | None = None
    buckets: str | None = None
    coalesce_ms: float = 2.0
    draw_thin: int = 1
    no_warmup: bool = False
    heartbeat_interval_s: float = 0.25
    heartbeat_timeout_s: float = 20.0
    startup_grace_s: float = 240.0
    restart_budget: int = 3
    backoff_base_s: float = 0.25
    backoff_factor: float = 2.0
    backoff_max_s: float = 30.0
    drain_timeout_s: float = 10.0
    request_timeout_s: float = 120.0
    flip_timeout_s: float = 240.0
    stats_interval_s: float = 5.0
    poll_s: float = 0.05

    def __post_init__(self):
        if int(self.replicas) < 1:
            raise ValueError(f"replicas={self.replicas} must be >= 1")
        if self.backoff_factor < 1.0:
            raise ValueError(
                f"backoff_factor={self.backoff_factor} must be >= 1")
        for f in ("heartbeat_interval_s", "heartbeat_timeout_s",
                  "startup_grace_s", "backoff_base_s", "backoff_max_s",
                  "drain_timeout_s", "request_timeout_s", "flip_timeout_s",
                  "poll_s"):
            if getattr(self, f) <= 0:
                raise ValueError(f"{f}={getattr(self, f)} must be > 0")
        if int(self.restart_budget) < 0:
            raise ValueError(
                f"restart_budget={self.restart_budget} must be >= 0")

    @classmethod
    def from_json(cls, path: str, **overrides) -> "ServeFleetConfig":
        """Load a config file, rejecting unknown keys loudly (a typo'd
        knob must not silently fall back to its default)."""
        with open(path) as f:
            doc = json.load(f)
        if not isinstance(doc, dict):
            raise ValueError(f"{path}: fleet config must be a JSON object")
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(doc) - known)
        if unknown:
            raise ValueError(
                f"{path}: unknown serve-fleet config key(s) {unknown}; "
                f"known keys: {sorted(known)}")
        doc.update(overrides)
        return cls(**doc)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class _Replica:
    """One supervised replica slot.  ``inflight``/``state`` are shared
    between the front-end handler threads and the watcher."""

    __slots__ = ("rank", "proc", "port", "inflight", "state", "fails",
                 "log_path", "next_spawn", "spawned_at", "pid",
                 "pre_flip_gen")

    def __init__(self, rank: int):
        self.rank = rank
        self.proc = None
        self.port = None
        self.inflight = 0
        self.state = "init"     # init|starting|live|backoff|dead|stopping
        self.fails = 0
        self.log_path = None
        self.next_spawn = 0.0
        self.spawned_at = 0.0
        self.pid = None
        self.pre_flip_gen = None


class ServingFleet:
    """Run a replicated serving fleet (see module docstring).

    Lifecycle: :meth:`start` spawns the replicas, the watcher, and the
    front end (bound to ``cfg.host:cfg.port``; the bound address is
    :attr:`url`); :meth:`flip` coordinates a fleet-wide epoch flip;
    :meth:`stop` drains and terminates everything.  Use as a context
    manager in tests."""

    # handler threads, the watcher, and flip() share the slot table;
    # `hmsc_tpu lint` (lock-discipline) enforces the declaration below
    # hmsc: guarded-by[_lock]: _n_proxied, _n_retried, _n_rejected

    def __init__(self, config: ServeFleetConfig):
        from ..obs import RunTelemetry
        from ..obs.trace import inherit_or_mint
        self.cfg = config
        self.telem = RunTelemetry(proc=0)
        # a serving fleet is a top-level entry point: replica lifecycles
        # and fleet-wide flips all link back to this trace
        self.trace = inherit_or_mint()
        self.telem.set_trace(self.trace)
        self.slots = [_Replica(r) for r in range(int(config.replicas))]
        self._lock = threading.Lock()
        self._flip_lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._watcher = None
        self._server = None
        self._server_thread = None
        self._rr = 0                  # round-robin tiebreak cursor
        self._n_proxied = 0
        self._n_retried = 0
        self._n_rejected = 0
        self._hb_dir = os.path.join(config.work_dir, "hb")
        self._last_stats = 0.0

    # -- event plumbing ----------------------------------------------------

    def _emit(self, name: str, **fields) -> None:
        self.telem.emit("fleet", name, **fields)
        self.telem.flush()            # the stream must be tailable live

    # -- replica lifecycle -------------------------------------------------

    def _spawn(self, slot: _Replica) -> None:
        cfg = self.cfg
        from ..utils.coordination import heartbeat_path
        # a SIGKILLed replica leaves its old heartbeat behind; spawning
        # over it would read a stale port — sweep before spawn
        try:
            os.unlink(heartbeat_path(self._hb_dir, slot.rank))
        except OSError:
            pass
        cmd = [sys.executable, "-m", "hmsc_tpu", "serve", cfg.source,
               "--host", cfg.host, "--port", "0",
               "--replica-rank", str(slot.rank),
               "--heartbeat-dir", self._hb_dir,
               "--heartbeat-interval-s", str(cfg.heartbeat_interval_s),
               "--coalesce-ms", str(cfg.coalesce_ms),
               "--draw-thin", str(cfg.draw_thin)]
        if cfg.buckets:
            cmd += ["--buckets", str(cfg.buckets)]
        if cfg.draw_shards:
            cmd += ["--draw-shards", str(cfg.draw_shards)]
        if cfg.no_warmup:
            cmd += ["--no-warmup"]
        slot.log_path = os.path.join(cfg.work_dir,
                                     f"replica-r{slot.rank}.log")
        # replica output goes to a file, not a pipe: a full pipe would
        # wedge a healthy replica while its heartbeat keeps beating
        logf = open(slot.log_path, "a")
        # the replica must import hmsc_tpu no matter where the parent's
        # cwd is (a user driving the fleet from a scratch dir imported
        # the package off sys.path, which children don't inherit)
        pkg_parent = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env = dict(os.environ)
        env["PYTHONPATH"] = (pkg_parent + os.pathsep + env["PYTHONPATH"]
                             if env.get("PYTHONPATH") else pkg_parent)
        # the replica joins the fleet trace: its per-request spans (and
        # the first query on a freshly flipped epoch) link back here
        from ..obs.trace import trace_env
        env.update(trace_env(self.trace))
        slot.proc = subprocess.Popen(cmd, stdout=logf,
                                     stderr=subprocess.STDOUT, env=env)
        logf.close()                  # the child holds its own descriptor
        slot.pid = slot.proc.pid
        slot.port = None
        slot.state = "starting"
        slot.spawned_at = time.monotonic()
        self._emit("replica_spawn", rank=slot.rank, pid=slot.pid,
                   fails=slot.fails)

    def _url(self, slot: _Replica) -> str:
        return f"http://{self.cfg.host}:{slot.port}"

    def _healthz(self, slot: _Replica, timeout: float = 2.0):
        """Best-effort /healthz read; ``None`` when unreachable."""
        import urllib.request
        if slot.port is None:
            return None
        try:
            with urllib.request.urlopen(self._url(slot) + "/healthz",
                                        timeout=timeout) as r:
                return json.loads(r.read().decode())
        except Exception:             # noqa: BLE001 — liveness probe
            return None

    def _on_exit(self, slot: _Replica, rc: int) -> None:
        cfg = self.cfg
        slot.proc = None
        slot.port = None
        self._emit("replica_exit", rank=slot.rank, rc=int(rc),
                   outcome=describe(rc),
                   log_tail=(log_tail(slot.log_path)
                             if rc != EXIT_OK else None))
        if self._stop_evt.is_set():
            slot.state = "stopping"
            return
        slot.fails += 1
        if slot.fails > cfg.restart_budget:
            slot.state = "dead"
            self._emit("replica_abandoned", rank=slot.rank,
                       fails=slot.fails, budget=cfg.restart_budget)
            return
        backoff = min(cfg.backoff_base_s
                      * cfg.backoff_factor ** (slot.fails - 1),
                      cfg.backoff_max_s)
        slot.state = "backoff"
        slot.next_spawn = time.monotonic() + backoff
        self._emit("replica_backoff", rank=slot.rank, fails=slot.fails,
                   backoff_s=round(backoff, 3))

    def _watch(self) -> None:
        from ..utils.coordination import read_heartbeats
        cfg = self.cfg
        while not self._stop_evt.is_set():
            now = time.monotonic()
            beats = read_heartbeats(self._hb_dir)
            for slot in self.slots:
                p = slot.proc
                if p is not None:
                    rc = p.poll()
                    if rc is not None and slot.state != "stopping":
                        self._on_exit(slot, rc)
                        continue
                if slot.state == "backoff" and now >= slot.next_spawn:
                    self._spawn(slot)
                    continue
                if slot.state == "starting":
                    hb = beats.get(slot.rank)
                    # the heartbeat file must postdate this spawn: a
                    # stale beat from the previous incarnation must not
                    # resurrect a dead port
                    if hb and "port" in hb \
                            and hb["mtime"] >= time.time() - (
                                now - slot.spawned_at) - 1.0:
                        slot.port = int(hb["port"])
                        if self._healthz(slot) is not None:
                            slot.state = "live"
                            self._emit("replica_ready", rank=slot.rank,
                                       port=slot.port, pid=slot.pid)
                        else:
                            slot.port = None
                    elif now - slot.spawned_at > cfg.startup_grace_s:
                        self._emit("replica_heartbeat_silent",
                                   rank=slot.rank, phase="startup",
                                   age_s=round(now - slot.spawned_at, 2))
                        self._kill(slot)
                elif slot.state == "live":
                    hb = beats.get(slot.rank)
                    if hb is None or hb["age_s"] > cfg.heartbeat_timeout_s:
                        self._emit("replica_heartbeat_silent",
                                   rank=slot.rank, phase="serving",
                                   age_s=(None if hb is None
                                          else round(hb["age_s"], 2)))
                        self._kill(slot)
            if now - self._last_stats >= cfg.stats_interval_s:
                self._last_stats = now
                self._emit_replica_stats()
            self._stop_evt.wait(cfg.poll_s)

    def _kill(self, slot: _Replica) -> None:
        p = slot.proc
        if p is not None and p.poll() is None:
            try:
                p.kill()
                p.wait(timeout=10.0)
            except OSError:
                pass

    def _emit_replica_stats(self) -> None:
        """Per-replica load sample for the report's qps/queue-wait skew:
        request counters plus the queue_wait span aggregate from each
        live replica's /statz."""
        import urllib.request
        for slot in self.slots:
            if slot.state != "live":
                continue
            try:
                with urllib.request.urlopen(self._url(slot) + "/statz",
                                            timeout=2.0) as r:
                    st = json.loads(r.read().decode())
            except Exception:         # noqa: BLE001 — stats are best-effort
                continue
            qw = (st.get("spans") or {}).get("queue_wait") or {}
            self._emit("replica_stats", rank=slot.rank,
                       requests=st.get("requests"),
                       rows_served=st.get("rows_served"),
                       generation=st.get("generation"),
                       epoch=st.get("epoch"),
                       queue_wait_s=qw.get("total_s"),
                       queue_wait_n=qw.get("count"),
                       inflight=slot.inflight)

    # -- dispatch ----------------------------------------------------------

    def _pick(self) -> _Replica | None:
        """Least-loaded live replica, round-robin on ties."""
        with self._lock:
            live = [s for s in self.slots if s.state == "live"]
            if not live:
                return None
            lo = min(s.inflight for s in live)
            cands = [s for s in live if s.inflight == lo]
            slot = cands[self._rr % len(cands)]
            self._rr += 1
            slot.inflight += 1
            return slot

    def _release(self, slot: _Replica) -> None:
        with self._lock:
            slot.inflight -= 1

    def _forward(self, method: str, path: str, body: bytes | None):
        """Proxy one query; retries connection-level failures on another
        live replica (an HTTP error status is a real answer and is
        forwarded as-is).  Returns ``(status, body_bytes)``."""
        import http.client
        import urllib.error
        import urllib.request
        cfg = self.cfg
        deadline = time.monotonic() + cfg.request_timeout_s
        attempt = 0
        while time.monotonic() < deadline:
            slot = self._pick()
            if slot is None:
                time.sleep(cfg.poll_s)  # mid-restart: wait for a replica
                continue
            attempt += 1
            try:
                req = urllib.request.Request(
                    self._url(slot) + path, data=body, method=method,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(
                        req, timeout=cfg.request_timeout_s) as r:
                    data = r.read()
                with self._lock:
                    self._n_proxied += 1
                return r.status, data
            except urllib.error.HTTPError as e:
                # the replica ANSWERED (4xx/5xx): forward, don't retry —
                # a bad query is bad on every replica
                data = e.read()
                with self._lock:
                    self._n_proxied += 1
                return e.code, data
            except (urllib.error.URLError, http.client.HTTPException,
                    ConnectionError, TimeoutError, OSError):
                # connection-level failure: the replica died under us
                # (chaos kill) — retry the query on another live replica
                with self._lock:
                    self._n_retried += 1
            finally:
                self._release(slot)
        with self._lock:
            self._n_rejected += 1
        return 503, json.dumps(
            {"error": "no live replica within request_timeout_s"}).encode()

    # -- front end ---------------------------------------------------------

    def _make_front(self):
        import http.server
        fleet = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: ARG002 — BaseHTTP
                pass

            def _send(self, code, payload):
                body = (payload if isinstance(payload, bytes)
                        else json.dumps(payload).encode())
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — BaseHTTP API
                if self.path == "/healthz":
                    self._send(200, fleet.health())
                elif self.path == "/statz":
                    self._send(200, fleet.stats())
                else:   # per-replica reads (e.g. /metrics) proxy through
                    self._send(*fleet._forward("GET", self.path, None))

            def do_POST(self):  # noqa: N802 — BaseHTTP API
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n) if n else b"{}"
                if self.path == "/flip":
                    try:
                        doc = json.loads(body.decode() or "{}")
                    except ValueError:
                        self._send(400, {"error": "invalid JSON"})
                        return
                    from ..obs.trace import from_header
                    tctx = from_header(
                        self.headers.get("X-Hmsc-Trace") or "")
                    try:
                        self._send(200, fleet.flip(
                            source=doc.get("source"),
                            warmup=bool(doc.get("warmup", True)),
                            trace=tctx))
                    except Exception as e:  # noqa: BLE001 — a failed flip
                        # answers 500; the fleet keeps serving the old epoch
                        self._send(500,
                                   {"error": f"{type(e).__name__}: {e}"})
                    return
                self._send(*fleet._forward("POST", self.path, body))

        return http.server.ThreadingHTTPServer(
            (self.cfg.host, int(self.cfg.port)), Handler)

    # -- public lifecycle --------------------------------------------------

    @property
    def url(self) -> str:
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    def start(self, wait_live: bool = True) -> "ServingFleet":
        cfg = self.cfg
        os.makedirs(cfg.work_dir, exist_ok=True)
        os.makedirs(self._hb_dir, exist_ok=True)
        self.telem.attach_sink(fleet_events_path(cfg.work_dir),
                               truncate=True)
        self._emit("serve_fleet_start", replicas=cfg.replicas,
                   source=str(cfg.source), draw_shards=cfg.draw_shards,
                   config=self.cfg.to_dict())
        for slot in self.slots:
            self._spawn(slot)
        self._watcher = threading.Thread(target=self._watch, daemon=True,
                                         name="hmsc-serve-fleet-watch")
        self._watcher.start()
        self._server = self._make_front()
        self._server_thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="hmsc-serve-fleet-front")
        self._server_thread.start()
        if wait_live:
            deadline = time.monotonic() + cfg.startup_grace_s
            while time.monotonic() < deadline:
                if all(s.state in ("live", "dead") for s in self.slots) \
                        and any(s.state == "live" for s in self.slots):
                    break
                time.sleep(cfg.poll_s)
            else:
                self.stop()
                raise TimeoutError(
                    f"serving fleet: no live replica within "
                    f"startup_grace_s={cfg.startup_grace_s}")
        return self

    def health(self) -> dict:
        """Fleet liveness + per-replica state (the front end's
        /healthz)."""
        reps = []
        for slot in self.slots:
            h = self._healthz(slot) if slot.state == "live" else None
            reps.append({"rank": slot.rank, "state": slot.state,
                         "port": slot.port, "pid": slot.pid,
                         "inflight": slot.inflight,
                         "generation": (h or {}).get("generation"),
                         "epoch": (h or {}).get("epoch")})
        return {"ok": any(s.state == "live" for s in self.slots),
                "replicas": reps, "fleet": True}

    def stats(self) -> dict:
        """Front-end counters + each live replica's engine stats (plus
        its heartbeat age, so /statz shows staleness per replica)."""
        with self._lock:
            counts = {"proxied": self._n_proxied,
                      "retried": self._n_retried,
                      "rejected": self._n_rejected}
        import urllib.request

        from ..utils.coordination import read_heartbeats
        beats = read_heartbeats(self._hb_dir)
        reps = {}
        for slot in self.slots:
            if slot.state != "live":
                continue
            try:
                with urllib.request.urlopen(self._url(slot) + "/statz",
                                            timeout=2.0) as r:
                    st = json.loads(r.read().decode())
            except Exception:         # noqa: BLE001 — stats best-effort
                continue
            hb = beats.get(slot.rank)
            st["last_beat_age_s"] = (None if hb is None
                                     else round(hb["age_s"], 3))
            reps[str(slot.rank)] = st
        return {"fleet": counts, "replicas": reps}

    # -- fleet-wide flip ---------------------------------------------------

    def flip(self, source=None, warmup: bool = True, trace=None) -> dict:
        """Rolling, generation-checked epoch flip across the fleet.

        Calls ``reload()`` on every rotation member in turn; each
        replica's flip response must advance its generation by exactly
        one (anything else is a coordination error).  The flip is
        acknowledged only when EVERY replica — including any that died
        and restarted mid-flip — reports the target epoch from
        ``/healthz``.  Returns the per-replica outcome summary.

        ``trace`` (a :class:`~hmsc_tpu.obs.trace.TraceContext`, e.g.
        parsed from the front end's ``X-Hmsc-Trace`` header) joins the
        flip events to the caller's trace — an autopilot rollout's flip
        lands in the SAME trace as the refit that produced the epoch."""
        import urllib.request
        cfg = self.cfg
        tf = trace.fields() if trace is not None else {}
        with self._flip_lock:         # one fleet-wide flip at a time
            t0 = time.monotonic()
            self._emit("flip_start", source=source, **tf)
            target_epoch = None
            outcomes = {}
            for slot in list(self.slots):
                if slot.state != "live":
                    outcomes[slot.rank] = slot.state
                    continue
                h0 = self._healthz(slot)
                pre_gen = None if h0 is None else h0.get("generation")
                payload = json.dumps(
                    {"source": source, "warmup": warmup}
                    if source is not None else
                    {"warmup": warmup}).encode()
                hdrs = {"Content-Type": "application/json"}
                if trace is not None:
                    hdrs["X-Hmsc-Trace"] = trace.header()
                try:
                    req = urllib.request.Request(
                        self._url(slot) + "/flip", data=payload,
                        headers=hdrs)
                    with urllib.request.urlopen(
                            req, timeout=cfg.flip_timeout_s) as r:
                        res = json.loads(r.read().decode())
                except Exception as e:  # noqa: BLE001 — a replica dying
                    # mid-flip is the chaos case: the watcher restarts it
                    # on the NEW epoch; the ack phase below waits for it
                    outcomes[slot.rank] = f"died ({type(e).__name__})"
                    self._emit("flip_replica", rank=slot.rank, ok=False,
                               error=type(e).__name__, **tf)
                    continue
                gen = res.get("generation")
                if pre_gen is not None and gen != pre_gen + 1:
                    raise RuntimeError(
                        f"replica {slot.rank}: flip answered generation "
                        f"{gen}, expected {pre_gen + 1} — a concurrent "
                        "flip raced this one")
                if res.get("epoch") is not None:
                    if target_epoch is not None \
                            and res["epoch"] != target_epoch:
                        raise RuntimeError(
                            f"replica {slot.rank} flipped to epoch "
                            f"{res['epoch']}, the fleet target is "
                            f"{target_epoch} — the source moved mid-flip")
                    target_epoch = res["epoch"]
                outcomes[slot.rank] = "flipped"
                self._emit("flip_replica", rank=slot.rank, ok=True,
                           generation=gen, epoch=res.get("epoch"),
                           shapes_changed=res.get("shapes_changed"), **tf)
            # ack phase: every slot that is (or comes back) live must
            # serve the target epoch before the flip is acknowledged
            deadline = time.monotonic() + cfg.flip_timeout_s
            pending = {s.rank for s in self.slots if s.state != "dead"}
            while pending and time.monotonic() < deadline:
                for slot in self.slots:
                    if slot.rank not in pending:
                        continue
                    if slot.state == "dead":
                        pending.discard(slot.rank)
                        continue
                    h = self._healthz(slot)
                    if h is None:
                        continue
                    if target_epoch is None or h.get("epoch") \
                            == target_epoch:
                        pending.discard(slot.rank)
                time.sleep(cfg.poll_s)
            ok = not pending
            self._emit("flip_done", ok=ok, epoch=target_epoch,
                       outcomes={str(k): v for k, v in outcomes.items()},
                       pending=sorted(pending),
                       wall_s=round(time.monotonic() - t0, 3), **tf)
            if not ok:
                raise TimeoutError(
                    f"fleet flip not acknowledged: replicas {sorted(pending)} "
                    f"did not reach epoch {target_epoch} within "
                    f"flip_timeout_s={cfg.flip_timeout_s}")
            return {"ok": True, "epoch": target_epoch,
                    "outcomes": {str(k): v for k, v in outcomes.items()},
                    "wall_s": round(time.monotonic() - t0, 3)}

    # -- shutdown ----------------------------------------------------------

    def stop(self) -> None:
        """Drain and terminate: rotation out first, bounded in-flight
        drain, SIGTERM (the replica's clean unwind), SIGKILL as the
        backstop."""
        if self._stop_evt.is_set():
            return
        self._stop_evt.set()
        if self._watcher is not None:
            self._watcher.join(timeout=10.0)
        for slot in self.slots:
            was = slot.state
            slot.state = "stopping"   # out of the rotation: no new queries
            if slot.proc is None or slot.proc.poll() is not None:
                continue
            deadline = time.monotonic() + self.cfg.drain_timeout_s
            while slot.inflight > 0 and time.monotonic() < deadline:
                time.sleep(self.cfg.poll_s)
            self._emit("replica_drain", rank=slot.rank, was=was,
                       inflight=slot.inflight)
            try:
                slot.proc.terminate()
                slot.proc.wait(timeout=10.0)
            except (OSError, subprocess.TimeoutExpired):
                self._kill(slot)
        if self._server is not None:
            self._server.shutdown()
            self._server_thread.join(timeout=10.0)
            self._server.server_close()
        self._emit_replica_stats()
        with self._lock:
            proxied, retried, rejected = (self._n_proxied, self._n_retried,
                                          self._n_rejected)
        self._emit("serve_fleet_end",
                   proxied=proxied, retried=retried, rejected=rejected)
        self.telem.flush()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.stop()
        return False


def serve_fleet_main(config_path: str, source_override=None) -> int:
    """``python -m hmsc_tpu serve --fleet config.json`` — run the fleet
    until SIGTERM/Ctrl-C."""
    import signal

    from ..obs import get_logger
    log = get_logger()
    overrides = {}
    if source_override is not None:
        overrides["source"] = source_override
    cfg = ServeFleetConfig.from_json(config_path, **overrides)
    fleet = ServingFleet(cfg)
    fleet.start(wait_live=True)
    host, port = fleet._server.server_address[:2]
    live = sum(s.state == "live" for s in fleet.slots)
    log.info(f"serve fleet: {live}/{cfg.replicas} replicas live behind "
             f"http://{host}:{port} (POST /predict, /flip; GET /healthz, "
             f"/statz) — events in "
             f"{fleet_events_path(cfg.work_dir)}")

    def _term(signum, frame):  # noqa: ARG001 — signal API
        raise KeyboardInterrupt
    old_term = signal.signal(signal.SIGTERM, _term)
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        log.info("serve fleet: interrupted, draining")
    finally:
        signal.signal(signal.SIGTERM, old_term)
        fleet.stop()
    return 0
