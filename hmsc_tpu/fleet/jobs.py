"""Fleet job-queue mode: shape-bucketed dispatch of batched fitting jobs.

``python -m hmsc_tpu fleet --jobs <dir>`` turns the PR 9 supervisor into a
scheduler for the multi-tenant batched sampler
(:func:`~hmsc_tpu.mcmc.multitenant.sample_mcmc_batched`): every ``*.json``
job file under the queue directory describes one small model; the queue
bins the jobs by padded-shape-bucket fingerprint
(:func:`~hmsc_tpu.mcmc.multitenant.bucket_key`) and dispatches each bucket
as ONE supervised worker subprocess running the vmapped pad-and-mask batch
— K tenants per chip-program instead of K serial runs.

Job file schema (one JSON object per file)::

    {"name": "regionA",                  # unique tenant name (default: stem)
     "model": {"ny": 40, "ns": 5, ...},  # build_worker_model kwargs
     "seed": 11}                         # per-tenant seed (default: stable
                                         #  hash of the name)

The run cadence (samples / transient / thin / n_chains /
checkpoint_every) is queue-wide, from the fleet config's ``run_kw`` —
bucketing requires a uniform cadence anyway.

Supervision mirrors the rank fleet: each bucket attempt is watched by exit
code, failures restart with exponential backoff under a per-bucket budget,
and every restart RESUMES from the bucket's per-tenant manifests (each
tenant continues from its own last committed mark — zero committed draws
lost for any tenant, by the same append-layout argument as the rank
fleet).  Every decision lands in ``fleet-events.jsonl``: per-bucket
``job_dispatch`` / ``job_exit``, per-tenant ``tenant_done`` completion
events, and a final ``queue_end`` carrying the batch ``report`` section
(per-bucket occupancy / padding-waste metrics).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

from ..exit_codes import EXIT_DIVERGED, EXIT_OK, describe

__all__ = ["JobQueue", "scan_jobs", "plan_buckets", "batch_worker_main",
           "bucket_ckpt_dir", "queue_status"]


def queue_status(outcomes: list[dict]) -> str:
    """The queue's failure class from its per-bucket outcomes — mapped to
    an exit code by the fleet CLI exactly like the rank supervisor's
    status ('diverged' -> 77, any other failure -> 1)."""
    bad = [o for o in outcomes if not o["ok"]]
    if not outcomes:
        return "empty-queue"
    if not bad:
        return "ok"
    if all(o["diverged"] for o in bad):
        # every failure is a surfaced divergence, not a supervision
        # failure — callers branch on 77 like the rank fleet's
        return "diverged"
    return "job-failed"


def bucket_ckpt_dir(root: str, bkey: str) -> str:
    return os.path.join(os.fspath(root), f"bucket-{bkey}")


def _job_seed(name: str) -> int:
    import hashlib
    return int.from_bytes(hashlib.sha256(name.encode()).digest()[:4],
                          "little") % (2**31 - 1)


def scan_jobs(jobs_dir: str) -> list[dict]:
    """Load every ``*.json`` job file under ``jobs_dir`` (sorted, so the
    queue order is deterministic).  Each job gets a unique ``name`` (file
    stem default) and a stable per-tenant ``seed``."""
    jobs, seen = [], set()
    for fn in sorted(os.listdir(jobs_dir)):
        if not fn.endswith(".json"):
            continue
        path = os.path.join(jobs_dir, fn)
        with open(path) as f:
            doc = json.load(f)
        if not isinstance(doc, dict):
            raise ValueError(f"{path}: job file must be a JSON object")
        name = str(doc.get("name", os.path.splitext(fn)[0]))
        if name in seen:
            raise ValueError(f"{path}: duplicate job name {name!r}")
        seen.add(name)
        jobs.append({"name": name, "model": dict(doc.get("model", {})),
                     "seed": int(doc.get("seed", _job_seed(name))),
                     "path": path})
    return jobs


def plan_buckets(jobs: list[dict], rounding: dict | None = None) -> dict:
    """Bin jobs by padded-shape-bucket fingerprint.  Builds each job's
    spec host-side (cheap — no sampling, no compile) and groups by
    :func:`~hmsc_tpu.mcmc.multitenant.bucket_key`."""
    from ..mcmc.multitenant import (batch_unsupported_reason, bucket_key)
    from ..mcmc.structs import build_model_data, build_spec
    from ..precompute import compute_data_parameters
    from ..testing.multiproc import build_worker_model

    buckets: dict[str, list[dict]] = {}
    for job in jobs:
        hM = build_worker_model(**job["model"])
        spec = build_spec(hM)
        reason = batch_unsupported_reason(spec)
        if reason is not None:
            raise ValueError(
                f"job {job['name']!r}: cannot join a padded batch "
                f"({reason})")
        data = build_model_data(hM, compute_data_parameters(hM), spec)
        buckets.setdefault(bucket_key(spec, data, rounding), []).append(job)
    return buckets


# ---------------------------------------------------------------------------
# the batch worker (one subprocess per dispatched bucket)
# ---------------------------------------------------------------------------

def batch_worker_main(argv=None) -> int:
    """One bucket's worker: build the tenants' models, run (or resume) the
    vmapped batched fit with per-tenant manifests, write the result record.
    Exit codes follow :mod:`hmsc_tpu.exit_codes`: 0 on success, 77 when
    any tenant completed diverged, 1 anything else."""
    import argparse

    ap = argparse.ArgumentParser(description="batched-bucket fitting worker")
    ap.add_argument("--jobs", required=True,
                    help="JSON list of job objects (name/model/seed)")
    ap.add_argument("--ckpt-dir", required=True,
                    help="this bucket's checkpoint root (per-tenant "
                         "manifests land in tenant-<name>/ under it)")
    ap.add_argument("--run", default="{}",
                    help="JSON kwargs for sample_mcmc_batched")
    ap.add_argument("--action", choices=("run", "resume"), default="run")
    ap.add_argument("--rounding", default=None,
                    help="JSON bucket_rounding override")
    ap.add_argument("--kill-at", type=int, default=None,
                    help="hard-kill (SIGKILL) once N samples are recorded "
                         "— the mid-run death the manifests must survive")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    import numpy as np

    from ..mcmc.multitenant import sample_mcmc_batched
    from ..obs import get_logger
    from ..testing.multiproc import build_worker_model

    jobs = json.loads(args.jobs)
    run_kw = dict(json.loads(args.run))
    run_kw.setdefault("samples", 8)
    run_kw.setdefault("checkpoint_every",
                      max(1, int(run_kw["samples"]) // 4))
    rounding = json.loads(args.rounding) if args.rounding else None

    models = [build_worker_model(**j.get("model", {})) for j in jobs]
    names = [j["name"] for j in jobs]
    seeds = [int(j.get("seed", _job_seed(j["name"]))) for j in jobs]

    if args.kill_at is not None:
        kill_at = int(args.kill_at)

        def progress_callback(done, total):
            if done >= kill_at:
                # the snapshot fan-out rides the background writer: wait
                # for every tenant's manifest to land so the drill tests
                # resume-from-manifest, not the trivial nothing-committed
                # fresh restart
                import glob
                import signal
                deadline = time.time() + 60.0
                names = [j["name"] for j in jobs]
                while time.time() < deadline:
                    if all(glob.glob(os.path.join(
                            args.ckpt_dir, f"tenant-{n}", "manifest-*"))
                            for n in names):
                        break
                    time.sleep(0.05)
                os.kill(os.getpid(), signal.SIGKILL)
    else:
        progress_callback = None

    try:
        posts, report = sample_mcmc_batched(
            models, names=names, seeds=seeds,
            checkpoint_path=args.ckpt_dir,
            resume=(args.action == "resume"),
            bucket_rounding=rounding,
            progress_callback=progress_callback,
            return_report=True, **run_kw)
    except Exception as e:            # noqa: BLE001 — the supervisor reads
        get_logger().warn(f"batch worker failed: {type(e).__name__}: {e}")
        raise

    tenants = []
    any_diverged = False
    for name, post in zip(names, posts):
        good = bool(np.asarray(post.chain_health["good_chains"]).all())
        any_diverged |= not good
        tenants.append({
            "tenant": name, "ok": good,
            "samples": int(post.samples), "n_chains": int(post.n_chains),
            "first_bad_it": [int(x) for x in
                             np.asarray(post.chain_health["first_bad_it"])],
            "digest": {k: float(np.asarray(v, dtype=np.float64).sum())
                       for k, v in post.arrays.items()},
        })
    if args.out:
        with open(args.out, "w") as f:
            json.dump({"tenants": tenants, "report": report}, f)
    return EXIT_DIVERGED if any_diverged else EXIT_OK


# ---------------------------------------------------------------------------
# the queue supervisor
# ---------------------------------------------------------------------------

class JobQueue:
    """Supervise a job-queue run: plan buckets, dispatch each as a watched
    worker subprocess, restart-with-resume on failure under a per-bucket
    budget, and emit the fleet event timeline + occupancy report."""

    def __init__(self, cfg, *, jobs_dir: str | None = None):
        from ..obs import RunTelemetry
        self.cfg = cfg
        self.jobs_dir = os.fspath(jobs_dir or cfg.jobs_dir)
        self.telem = RunTelemetry(proc=0)
        self.attempt_log: list = []
        self._t0 = time.monotonic()

    def _emit(self, name: str, **fields) -> None:
        self.telem.emit("fleet", name, **fields)
        self.telem.flush()            # the stream must be tailable live

    def _spawn(self, bkey: str, jobs: list, action: str, attempt: int,
               kill_at: int | None = None):
        from ..testing.multiproc import _pkg_root, worker_env
        cfg = self.cfg
        out = os.path.join(cfg.work_dir, f"job-{bkey}-{attempt:03d}.json")
        cmd = [sys.executable, "-c",
               "from hmsc_tpu.fleet.jobs import batch_worker_main; "
               "raise SystemExit(batch_worker_main())",
               "--jobs", json.dumps([{k: v for k, v in j.items()
                                      if k != "path"} for j in jobs]),
               "--ckpt-dir", bucket_ckpt_dir(cfg.ckpt_dir, bkey),
               "--run", json.dumps(cfg.run_kw),
               "--action", action, "--out", out]
        if getattr(cfg, "bucket_rounding", None):
            cmd += ["--rounding", json.dumps(cfg.bucket_rounding)]
        if kill_at is not None:
            cmd += ["--kill-at", str(int(kill_at))]
        log_path = os.path.join(cfg.work_dir,
                                f"job-{bkey}-{attempt:03d}.log")
        logf = open(log_path, "w")
        p = subprocess.Popen(cmd, cwd=_pkg_root(), env=worker_env(),
                             stdout=logf, stderr=subprocess.STDOUT)
        logf.close()
        self._emit("job_dispatch", bucket=bkey, attempt=attempt, pid=p.pid,
                   action=action, n_tenants=len(jobs),
                   tenants=[j["name"] for j in jobs])
        return p, out, log_path

    def _run_bucket_supervised(self, bkey: str, jobs: list,
                               chaos_kill_at=None) -> dict:
        """Dispatch one bucket to completion under the restart budget.
        ``chaos_kill_at`` arms a first-attempt mid-run SIGKILL (the chaos
        drill: the retry must resume from per-tenant manifests with zero
        committed draws lost)."""
        from ..utils.checkpoint import checkpoint_files
        cfg = self.cfg
        budget = int(cfg.restart_budget)
        attempt = 0
        result = None
        diverged = False
        while True:
            attempt += 1
            ck_root = bucket_ckpt_dir(cfg.ckpt_dir, bkey)
            has_ck = any(
                checkpoint_files(os.path.join(ck_root, d))
                for d in (os.listdir(ck_root)
                          if os.path.isdir(ck_root) else [])
                if d.startswith("tenant-"))
            action = "resume" if has_ck else "run"
            kill = chaos_kill_at if attempt == 1 else None
            p, out, log_path = self._spawn(bkey, jobs, action, attempt,
                                           kill_at=kill)
            try:
                rc = p.wait(timeout=cfg.wall_timeout_s)
            except subprocess.TimeoutExpired:
                p.kill()
                rc = p.wait()
            rec = None
            if os.path.exists(out):
                try:
                    with open(out) as f:
                        rec = json.load(f)
                except (OSError, ValueError):
                    rec = None
            self._emit("job_exit", bucket=bkey, attempt=attempt, rc=int(rc),
                       outcome=describe(rc))
            self.attempt_log.append({"bucket": bkey, "attempt": attempt,
                                     "action": action, "rc": int(rc)})
            if rc == EXIT_OK and rec is not None:
                result = rec
                break
            if rc == EXIT_DIVERGED and rec is not None:
                # deterministic blow-ups recur; surface instead of burning
                # the budget (mirrors the rank fleet's policy)
                result = rec
                diverged = True
                break
            budget -= 1
            if budget <= 0:
                self._emit("job_abort", bucket=bkey,
                           reason="budget-exhausted", attempts=attempt)
                break
            backoff = min(cfg.backoff_base_s
                          * cfg.backoff_factor ** (attempt - 1),
                          cfg.backoff_max_s)
            self._emit("backoff", bucket=bkey, seconds=round(backoff, 3))
            time.sleep(backoff)
        if result is not None:
            for trec in result.get("tenants", []):
                self._emit("tenant_done", bucket=bkey, **trec)
        return {"bucket": bkey, "attempts": attempt,
                "ok": result is not None
                and all(t["ok"] for t in result.get("tenants", [])),
                "diverged": diverged, "result": result}

    def run(self, chaos_kill_at=None) -> dict:
        """Run the whole queue: scan, plan, dispatch every bucket.
        Returns the summary dict the CLI prints (with the batch ``report``
        section: per-bucket occupancy and padding waste)."""
        from .supervisor import fleet_events_path
        cfg = self.cfg
        os.makedirs(cfg.work_dir, exist_ok=True)
        os.makedirs(cfg.ckpt_dir, exist_ok=True)
        self.telem.attach_sink(fleet_events_path(cfg.ckpt_dir),
                               truncate=True)
        jobs = scan_jobs(self.jobs_dir)
        buckets = plan_buckets(jobs, getattr(cfg, "bucket_rounding", None))
        self._emit("queue_start", n_jobs=len(jobs), n_buckets=len(buckets),
                   buckets={k: [j["name"] for j in v]
                            for k, v in sorted(buckets.items())})
        outcomes = []
        for bkey, bjobs in sorted(buckets.items()):
            outcomes.append(self._run_bucket_supervised(
                bkey, bjobs, chaos_kill_at=chaos_kill_at))
        report = {"buckets": [], "occupancy": None, "padding_waste": None}
        cr = cp = 0
        for o in outcomes:
            rep = (o["result"] or {}).get("report") or {}
            for b in rep.get("buckets", []):
                report["buckets"].append(b)
                cr += b.get("cells_real", 0)
                cp += b.get("cells_padded", 0)
        if cp:
            report["occupancy"] = round(cr / cp, 4)
            report["padding_waste"] = round(1.0 - cr / cp, 4)
        status = queue_status(outcomes)
        summary = {
            "ok": status == "ok",
            "status": status,
            "n_jobs": len(jobs), "n_buckets": len(buckets),
            "bucket_outcomes": [{k: v for k, v in o.items()
                                 if k != "result"} for o in outcomes],
            "tenants_done": sum(
                len((o["result"] or {}).get("tenants", []))
                for o in outcomes),
            "report": report,
            "wall_s": round(time.monotonic() - self._t0, 3),
        }
        self._emit("queue_end", **summary)
        return summary


if __name__ == "__main__":
    raise SystemExit(batch_worker_main())
