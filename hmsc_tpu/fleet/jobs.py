"""Fleet job-queue mode: shape-bucketed dispatch of batched fitting jobs.

``python -m hmsc_tpu fleet --jobs <dir>`` turns the PR 9 supervisor into a
scheduler for the multi-tenant batched sampler
(:func:`~hmsc_tpu.mcmc.multitenant.sample_mcmc_batched`): every ``*.json``
job file under the queue directory describes one small model; the queue
bins the jobs by padded-shape-bucket fingerprint
(:func:`~hmsc_tpu.mcmc.multitenant.bucket_key`) and dispatches each bucket
as ONE supervised worker subprocess running the vmapped pad-and-mask batch
— K tenants per chip-program instead of K serial runs.

Job file schema (one JSON object per file)::

    {"name": "regionA",                  # unique tenant name (default: stem)
     "model": {"ny": 40, "ns": 5, ...},  # build_worker_model kwargs
     "seed": 11,                         # per-tenant seed (default: stable
                                         #  hash of the name)
     "type": "fit"}                      # "fit" | "cv" | "waic" | "gradient"

The run cadence (samples / transient / thin / n_chains /
checkpoint_every) is queue-wide, from the fleet config's ``run_kw`` —
bucketing requires a uniform cadence anyway.

**Scenario jobs** (the scenario engine): beyond the plain ``fit``, three
embarrassingly parallel scenario types ride the same bucketed batched
dispatch:

- ``{"type": "cv", "nfolds": 5}`` — k-fold cross-validation.  The job
  expands into one tenant per fold (``<name>@cv<k>``), each a training
  refit binned by the SAME bucket fingerprinting as any other tenant
  (equal-shape folds share one vmapped program), with the fold partition
  and all per-fold seeds drawn from the job seed in EXACTLY
  :func:`~hmsc_tpu.predict.cv.compute_predicted_values`'s consumption
  order — a zero-pad scenario CV reproduces the serial path bit for bit.
  Each fold's held-out predictions are reduced to their posterior mean in
  the worker and re-assembled into the serial function's (ny, ns)
  predicted-value matrix at aggregation time.
- ``{"type": "waic"}`` — fit plus a
  :func:`~hmsc_tpu.post.metrics.compute_waic` evaluation; a sweep of waic
  jobs over model variants is a model-selection comparison.
- ``{"type": "gradient", "focal": 1, "ngrid": 8}`` — fit plus a
  counterfactual grid: the focal design column sweeps its observed range
  over ``ngrid`` points with every other column at its training mean
  (the raw-matrix analogue of ``construct_gradient``, which requires a
  formula-built model), predicted at the level's first training unit.

Scenario results aggregate into the summary's ``scenarios`` section, one
``scenario_done`` fleet event per scenario job, rendered by
``python -m hmsc_tpu report <dir> --scenarios``.

Supervision mirrors the rank fleet: each bucket attempt is watched by exit
code, failures restart with exponential backoff under a per-bucket budget,
and every restart RESUMES from the bucket's per-tenant manifests (each
tenant continues from its own last committed mark — zero committed draws
lost for any tenant, by the same append-layout argument as the rank
fleet).  Every decision lands in ``fleet-events.jsonl``: per-bucket
``job_dispatch`` / ``job_exit``, per-tenant ``tenant_done`` completion
events, and a final ``queue_end`` carrying the batch ``report`` section
(per-bucket occupancy / padding-waste metrics).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

from ..exit_codes import EXIT_DIVERGED, EXIT_OK, describe

__all__ = ["JobQueue", "scan_jobs", "plan_buckets", "batch_worker_main",
           "bucket_ckpt_dir", "queue_status", "build_tenant_model",
           "expand_scenarios"]

SCENARIO_TYPES = ("fit", "cv", "waic", "gradient")


def queue_status(outcomes: list[dict]) -> str:
    """The queue's failure class from its per-bucket outcomes — mapped to
    an exit code by the fleet CLI exactly like the rank supervisor's
    status ('diverged' -> 77, any other failure -> 1)."""
    bad = [o for o in outcomes if not o["ok"]]
    if not outcomes:
        return "empty-queue"
    if not bad:
        return "ok"
    if all(o["diverged"] for o in bad):
        # every failure is a surfaced divergence, not a supervision
        # failure — callers branch on 77 like the rank fleet's
        return "diverged"
    return "job-failed"


def bucket_ckpt_dir(root: str, bkey: str) -> str:
    return os.path.join(os.fspath(root), f"bucket-{bkey}")


def _job_seed(name: str) -> int:
    import hashlib
    return int.from_bytes(hashlib.sha256(name.encode()).digest()[:4],
                          "little") % (2**31 - 1)


def scan_jobs(jobs_dir: str) -> list[dict]:
    """Load every ``*.json`` job file under ``jobs_dir`` (sorted, so the
    queue order is deterministic).  Each job gets a unique ``name`` (file
    stem default), a stable per-tenant ``seed``, a scenario ``type``
    (default ``"fit"``) and the type's parameters."""
    jobs, seen = [], set()
    for fn in sorted(os.listdir(jobs_dir)):
        if not fn.endswith(".json"):
            continue
        path = os.path.join(jobs_dir, fn)
        with open(path) as f:
            doc = json.load(f)
        if not isinstance(doc, dict):
            raise ValueError(f"{path}: job file must be a JSON object")
        name = str(doc.get("name", os.path.splitext(fn)[0]))
        if name in seen:
            raise ValueError(f"{path}: duplicate job name {name!r}")
        seen.add(name)
        typ = str(doc.get("type", "fit"))
        if typ not in SCENARIO_TYPES:
            raise ValueError(f"{path}: unknown job type {typ!r} "
                             f"(one of {SCENARIO_TYPES})")
        params = {k: doc[k] for k in ("nfolds", "focal", "ngrid")
                  if k in doc}
        jobs.append({"name": name, "model": dict(doc.get("model", {})),
                     "seed": int(doc.get("seed", _job_seed(name))),
                     "type": typ, "params": params,
                     "path": path})
    return jobs


def build_tenant_model(job: dict):
    """The tenant's Hmsc model: the base worker model from the job's
    ``model`` kwargs, restricted to the fold's TRAINING rows when the
    tenant is a CV-fold expansion (same rebuild as the serial CV path's
    :func:`~hmsc_tpu.predict.cv._fold_model`, scaling copied verbatim)."""
    from ..testing.multiproc import build_worker_model

    hM = build_worker_model(**job.get("model", {}))
    sc = job.get("scenario") or {}
    if sc.get("kind") == "cv_fold":
        import numpy as np

        from ..predict.cv import _fold_model
        part = np.asarray(sc["partition"])
        return _fold_model(hM, part != int(sc["fold"]))
    return hM


def expand_scenarios(jobs: list[dict]) -> list[dict]:
    """Expand scenario jobs into the flat per-tenant job list the planner
    buckets.  ``fit`` jobs pass through; ``waic`` / ``gradient`` jobs stay
    one tenant carrying an evaluation spec; ``cv`` jobs expand into one
    tenant per fold (``<name>@cv<k>``).

    The CV expansion draws from ``default_rng(job seed)`` in EXACTLY
    :func:`~hmsc_tpu.predict.cv.compute_predicted_values`'s consumption
    order — partition first, then per sorted fold a fit seed followed by a
    predict seed — so a zero-pad bucket reproduces the serial CV bit for
    bit from the same job seed."""
    import numpy as np

    from ..predict.cv import create_partition
    from ..testing.multiproc import build_worker_model

    out = []
    for job in jobs:
        typ = job.get("type", "fit")
        base = {k: v for k, v in job.items() if k not in ("type", "params")}
        params = job.get("params", {})
        if typ == "cv":
            nfolds = int(params.get("nfolds", 5))
            rng = np.random.default_rng(int(job["seed"]))
            hM = build_worker_model(**job.get("model", {}))
            part = create_partition(hM, nfolds, rng=rng)
            for k in np.unique(part):
                fit_seed = int(rng.integers(2**31))
                predict_seed = int(rng.integers(2**31))
                out.append(dict(
                    base, name=f"{job['name']}@cv{int(k)}", seed=fit_seed,
                    scenario={"kind": "cv_fold", "parent": job["name"],
                              "fold": int(k), "nfolds": nfolds,
                              "partition": [int(x) for x in part],
                              "predict_seed": predict_seed}))
        elif typ == "waic":
            out.append(dict(base,
                            scenario={"kind": "waic",
                                      "parent": job["name"]}))
        elif typ == "gradient":
            out.append(dict(
                base,
                scenario={"kind": "gradient", "parent": job["name"],
                          "focal": int(params.get("focal", 1)),
                          "ngrid": int(params.get("ngrid", 8)),
                          "predict_seed":
                              _job_seed(f"{job['name']}:gradient")}))
        else:
            out.append(dict(base))
    return out


# heavy per-tenant scenario payload fields that stay in the worker result
# JSON (and the queue summary) but are stripped from streamed fleet events
_SCENARIO_HEAVY = ("partition", "val_rows", "pred_mean", "grid", "grid_pred")


def _evaluate_scenario(job: dict, hM, post) -> dict | None:
    """Evaluate one tenant's scenario payload against its fitted posterior
    (runs inside the batch worker).  ``hM`` is the tenant's model as built
    by :func:`build_tenant_model` (the FOLD model for cv_fold tenants)."""
    sc = job.get("scenario") or {}
    kind = sc.get("kind")
    if not kind:
        return None
    import numpy as np

    if kind == "waic":
        from ..post.metrics import compute_waic
        return {"kind": "waic", "parent": sc["parent"],
                "waic": float(compute_waic(post))}

    import pandas as pd

    from ..predict.predict import predict
    from ..testing.multiproc import build_worker_model

    parent = build_worker_model(**job.get("model", {}))
    if kind == "cv_fold":
        part = np.asarray(sc["partition"])
        val = part == int(sc["fold"])
        sd_val = (pd.DataFrame({name: np.asarray(parent.df_pi[r])[val]
                                for r, name in enumerate(parent.rl_names)})
                  if parent.nr > 0 else None)
        X_val = (list(parent.X[:, val, :]) if parent.x_is_list
                 else parent.X[val])
        XRRR_val = None if parent.nc_rrr == 0 else parent.XRRR[val]
        pred = np.asarray(predict(
            post, X=X_val, XRRR=XRRR_val, study_design=sd_val,
            mcmc_step=1, expected=True, seed=int(sc["predict_seed"])))
        pm = pred.mean(axis=0)
        resid = pm - parent.Y[val]
        sse = float(np.nansum(resid ** 2))
        n = int(np.isfinite(parent.Y[val]).sum())
        return {"kind": "cv_fold", "parent": sc["parent"],
                "fold": int(sc["fold"]), "nfolds": int(sc["nfolds"]),
                "val_rows": [int(i) for i in np.flatnonzero(val)],
                "pred_mean": pm.tolist(), "sse": sse, "n": n}

    if kind == "gradient":
        # raw-matrix counterfactual grid: construct_gradient needs a
        # formula-built model, so sweep the focal column over its observed
        # range with every other column held at its training mean, pinned
        # to each level's first training unit (study_design=None would
        # reuse the TRAINING labels, whose length mismatches the grid)
        focal = int(sc["focal"])
        ngrid = int(sc["ngrid"])
        Xb = parent.X[0] if parent.x_is_list else parent.X
        grid = np.linspace(float(Xb[:, focal].min()),
                           float(Xb[:, focal].max()), ngrid)
        Xg = np.tile(np.asarray(Xb).mean(axis=0), (ngrid, 1))
        Xg[:, focal] = grid
        sd = (pd.DataFrame({name: [np.asarray(parent.df_pi[r])[0]] * ngrid
                            for r, name in enumerate(parent.rl_names)})
              if parent.nr > 0 else None)
        XRRRg = (np.tile(np.asarray(parent.XRRR).mean(axis=0), (ngrid, 1))
                 if parent.nc_rrr > 0 else None)
        pred = np.asarray(predict(
            post, X=list(np.broadcast_to(Xg, (len(parent.X), *Xg.shape)))
            if parent.x_is_list else Xg,
            XRRR=XRRRg, study_design=sd, mcmc_step=1, expected=True,
            seed=int(sc["predict_seed"])))
        return {"kind": "gradient", "parent": sc["parent"], "focal": focal,
                "ngrid": ngrid, "grid": grid.tolist(),
                "grid_pred": pred.mean(axis=0).tolist()}

    raise ValueError(f"unknown scenario kind {kind!r}")


def plan_buckets(jobs: list[dict], rounding: dict | None = None) -> dict:
    """Bin (already scenario-expanded) jobs by padded-shape-bucket
    fingerprint.  Builds each tenant's spec host-side (cheap — no
    sampling, no compile) and groups by
    :func:`~hmsc_tpu.mcmc.multitenant.bucket_key`.  CV-fold tenants get
    their FOLD model's fingerprint, so equal-shape folds land in one
    bucket and batch into a single vmapped program."""
    from ..mcmc.multitenant import (batch_unsupported_reason, bucket_key)
    from ..mcmc.structs import build_model_data, build_spec
    from ..precompute import compute_data_parameters

    buckets: dict[str, list[dict]] = {}
    for job in jobs:
        hM = build_tenant_model(job)
        spec = build_spec(hM)
        reason = batch_unsupported_reason(spec)
        if reason is not None:
            raise ValueError(
                f"job {job['name']!r}: cannot join a padded batch "
                f"({reason})")
        data = build_model_data(hM, compute_data_parameters(hM), spec)
        buckets.setdefault(bucket_key(spec, data, rounding), []).append(job)
    return buckets


# ---------------------------------------------------------------------------
# the batch worker (one subprocess per dispatched bucket)
# ---------------------------------------------------------------------------

def batch_worker_main(argv=None) -> int:
    """One bucket's worker: build the tenants' models, run (or resume) the
    vmapped batched fit with per-tenant manifests, write the result record.
    Exit codes follow :mod:`hmsc_tpu.exit_codes`: 0 on success, 77 when
    any tenant completed diverged, 1 anything else."""
    import argparse

    ap = argparse.ArgumentParser(description="batched-bucket fitting worker")
    ap.add_argument("--jobs", default=None,
                    help="JSON list of job objects (name/model/seed)")
    ap.add_argument("--buckets", default=None,
                    help="JSON list of bucket specs ({bkey, jobs, "
                         "ckpt_dir, action, out}) to run back to back in "
                         "THIS process — the grouped dispatch that "
                         "amortizes start-up across a sweep's buckets")
    ap.add_argument("--ckpt-dir", default=None,
                    help="this bucket's checkpoint root (per-tenant "
                         "manifests land in tenant-<name>/ under it); "
                         "grouped dispatch carries it per bucket spec")
    ap.add_argument("--run", default="{}",
                    help="JSON kwargs for sample_mcmc_batched")
    ap.add_argument("--action", choices=("run", "resume"), default="run")
    ap.add_argument("--rounding", default=None,
                    help="JSON bucket_rounding override")
    ap.add_argument("--kill-at", type=int, default=None,
                    help="hard-kill (SIGKILL) once N samples are recorded "
                         "— the mid-run death the manifests must survive")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    run_kw = dict(json.loads(args.run))
    run_kw.setdefault("samples", 8)
    run_kw.setdefault("checkpoint_every",
                      max(1, int(run_kw["samples"]) // 4))
    rounding = json.loads(args.rounding) if args.rounding else None

    if args.jobs is None and args.buckets is None:
        ap.error("one of --jobs / --buckets is required")

    if args.buckets is not None:
        any_diverged = False
        for spec in json.loads(args.buckets):
            rec = _run_worker_bucket(spec["jobs"], spec["ckpt_dir"],
                                     run_kw, spec.get("action", "run"),
                                     rounding, None)
            any_diverged |= not all(t["ok"] for t in rec["tenants"])
            with open(spec["out"], "w") as f:
                json.dump(rec, f)
        return EXIT_DIVERGED if any_diverged else EXIT_OK

    jobs = json.loads(args.jobs)

    if args.kill_at is not None:
        kill_at = int(args.kill_at)

        def progress_callback(done, total):
            if done >= kill_at:
                # the snapshot fan-out rides the background writer: wait
                # for every tenant's manifest to land so the drill tests
                # resume-from-manifest, not the trivial nothing-committed
                # fresh restart
                import glob
                import signal
                deadline = time.time() + 60.0
                names = [j["name"] for j in jobs]
                while time.time() < deadline:
                    if all(glob.glob(os.path.join(
                            args.ckpt_dir, f"tenant-{n}", "manifest-*"))
                            for n in names):
                        break
                    time.sleep(0.05)
                os.kill(os.getpid(), signal.SIGKILL)
    else:
        progress_callback = None

    rec = _run_worker_bucket(jobs, args.ckpt_dir, run_kw, args.action,
                             rounding, progress_callback)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(rec, f)
    return (EXIT_OK if all(t["ok"] for t in rec["tenants"])
            else EXIT_DIVERGED)


def _run_worker_bucket(jobs: list[dict], ckpt_dir: str, run_kw: dict,
                       action: str, rounding, progress_callback) -> dict:
    """Fit one bucket's tenants (batched) and evaluate their scenarios;
    returns the result record the supervisor reads (``tenants`` +
    occupancy ``report``).  Shared by the one-bucket-per-process dispatch
    and the grouped (many buckets, one process) dispatch."""
    import numpy as np

    from ..mcmc.multitenant import sample_mcmc_batched
    from ..obs import get_logger

    models = [build_tenant_model(j) for j in jobs]
    names = [j["name"] for j in jobs]
    seeds = [int(j.get("seed", _job_seed(j["name"]))) for j in jobs]

    try:
        posts, report = sample_mcmc_batched(
            models, names=names, seeds=seeds,
            checkpoint_path=ckpt_dir,
            resume=(action == "resume"),
            bucket_rounding=rounding,
            progress_callback=progress_callback,
            return_report=True, **run_kw)
    except Exception as e:            # noqa: BLE001 — the supervisor reads
        get_logger().warn(f"batch worker failed: {type(e).__name__}: {e}")
        raise

    tenants = []
    for job, hM, name, post in zip(jobs, models, names, posts):
        good = bool(np.asarray(post.chain_health["good_chains"]).all())
        trec = {
            "tenant": name, "ok": good,
            "samples": int(post.samples), "n_chains": int(post.n_chains),
            "first_bad_it": [int(x) for x in
                             np.asarray(post.chain_health["first_bad_it"])],
            "digest": {k: float(np.asarray(v, dtype=np.float64).sum())
                       for k, v in post.arrays.items()},
        }
        if job.get("scenario"):
            if good:
                trec["scenario"] = _evaluate_scenario(job, hM, post)
            else:
                # no finite draws worth evaluating — mirror the serial CV
                # path's all-diverged refusal, but per tenant
                trec["scenario"] = dict(job["scenario"], failed=True)
        tenants.append(trec)
    return {"tenants": tenants, "report": report}


# ---------------------------------------------------------------------------
# the queue supervisor
# ---------------------------------------------------------------------------

class JobQueue:
    """Supervise a job-queue run: plan buckets, dispatch each as a watched
    worker subprocess, restart-with-resume on failure under a per-bucket
    budget, and emit the fleet event timeline + occupancy report."""

    def __init__(self, cfg, *, jobs_dir: str | None = None):
        from ..obs import RunTelemetry
        from ..obs.trace import inherit_or_mint
        self.cfg = cfg
        self.jobs_dir = os.fspath(jobs_dir or cfg.jobs_dir)
        self.telem = RunTelemetry(proc=0)
        # a queue run is a top-level entry point: every dispatch and every
        # bucket worker's event stream (incl. per-tenant scenario folds)
        # links back to this trace
        self.trace = inherit_or_mint()
        self.telem.set_trace(self.trace)
        self.attempt_log: list = []
        self._t0 = time.monotonic()

    def _emit(self, name: str, **fields) -> None:
        self.telem.emit("fleet", name, **fields)
        self.telem.flush()            # the stream must be tailable live

    def _spawn(self, bkey: str, jobs: list, action: str, attempt: int,
               kill_at: int | None = None):
        from ..testing.multiproc import _pkg_root, worker_env
        cfg = self.cfg
        out = os.path.join(cfg.work_dir, f"job-{bkey}-{attempt:03d}.json")
        cmd = [sys.executable, "-c",
               "from hmsc_tpu.fleet.jobs import batch_worker_main; "
               "raise SystemExit(batch_worker_main())",
               "--jobs", json.dumps([{k: v for k, v in j.items()
                                      if k != "path"} for j in jobs]),
               "--ckpt-dir", bucket_ckpt_dir(cfg.ckpt_dir, bkey),
               "--run", json.dumps(cfg.run_kw),
               "--action", action, "--out", out]
        if getattr(cfg, "bucket_rounding", None):
            cmd += ["--rounding", json.dumps(cfg.bucket_rounding)]
        if kill_at is not None:
            cmd += ["--kill-at", str(int(kill_at))]
        log_path = os.path.join(cfg.work_dir,
                                f"job-{bkey}-{attempt:03d}.log")
        logf = open(log_path, "w")
        # per-dispatch child span: the worker inherits it via the env, so
        # the bucket's sampler stream parents under THIS dispatch event
        ctx = self.trace.child()
        p = subprocess.Popen(cmd, cwd=_pkg_root(), env=worker_env(trace=ctx),
                             stdout=logf, stderr=subprocess.STDOUT)
        logf.close()
        self._emit("job_dispatch", bucket=bkey, attempt=attempt, pid=p.pid,
                   action=action, n_tenants=len(jobs),
                   tenants=[j["name"] for j in jobs],
                   span=ctx.span_id, parent=self.trace.span_id)
        return p, out, log_path

    def _run_bucket_supervised(self, bkey: str, jobs: list,
                               chaos_kill_at=None) -> dict:
        """Dispatch one bucket to completion under the restart budget.
        ``chaos_kill_at`` arms a first-attempt mid-run SIGKILL (the chaos
        drill: the retry must resume from per-tenant manifests with zero
        committed draws lost)."""
        from ..utils.checkpoint import checkpoint_files
        cfg = self.cfg
        budget = int(cfg.restart_budget)
        attempt = 0
        result = None
        diverged = False
        while True:
            attempt += 1
            ck_root = bucket_ckpt_dir(cfg.ckpt_dir, bkey)
            has_ck = any(
                checkpoint_files(os.path.join(ck_root, d))
                for d in (os.listdir(ck_root)
                          if os.path.isdir(ck_root) else [])
                if d.startswith("tenant-"))
            action = "resume" if has_ck else "run"
            kill = chaos_kill_at if attempt == 1 else None
            p, out, log_path = self._spawn(bkey, jobs, action, attempt,
                                           kill_at=kill)
            try:
                rc = p.wait(timeout=cfg.wall_timeout_s)
            except subprocess.TimeoutExpired:
                p.kill()
                rc = p.wait()
            rec = None
            if os.path.exists(out):
                try:
                    with open(out) as f:
                        rec = json.load(f)
                except (OSError, ValueError):
                    rec = None
            self._emit("job_exit", bucket=bkey, attempt=attempt, rc=int(rc),
                       outcome=describe(rc))
            self.attempt_log.append({"bucket": bkey, "attempt": attempt,
                                     "action": action, "rc": int(rc)})
            if rc == EXIT_OK and rec is not None:
                result = rec
                break
            if rc == EXIT_DIVERGED and rec is not None:
                # deterministic blow-ups recur; surface instead of burning
                # the budget (mirrors the rank fleet's policy)
                result = rec
                diverged = True
                break
            budget -= 1
            if budget <= 0:
                self._emit("job_abort", bucket=bkey,
                           reason="budget-exhausted", attempts=attempt)
                break
            backoff = min(cfg.backoff_base_s
                          * cfg.backoff_factor ** (attempt - 1),
                          cfg.backoff_max_s)
            self._emit("backoff", bucket=bkey, seconds=round(backoff, 3))
            time.sleep(backoff)
        if result is not None:
            self._emit_bucket_done(bkey, result)
        return {"bucket": bkey, "attempts": attempt,
                "ok": result is not None
                and all(t["ok"] for t in result.get("tenants", [])),
                "diverged": diverged, "result": result}

    def _emit_bucket_done(self, bkey: str, result: dict) -> None:
        for trec in result.get("tenants", []):
            ev = dict(trec)
            if ev.get("scenario"):
                # the streamed event keeps the scenario verdict but not
                # the bulk payload (fold partitions, prediction grids)
                ev["scenario"] = {k: v for k, v in ev["scenario"].items()
                                  if k not in _SCENARIO_HEAVY}
            self._emit("tenant_done", bucket=bkey, **ev)

    def _run_buckets_grouped(self, buckets: dict,
                             chaos_kill_at=None) -> list[dict]:
        """Dispatch EVERY bucket to one worker process per attempt (the
        ``group_buckets`` mode): interpreter/JAX start-up is paid once per
        sweep instead of once per bucket.  The worker writes one result
        record per completed bucket, so a retry re-dispatches only the
        buckets without a result — per-bucket fault isolation survives
        grouping."""
        from ..utils.checkpoint import checkpoint_files
        cfg = self.cfg
        pending = dict(sorted(buckets.items()))
        done: dict = {}
        budget = int(cfg.restart_budget)
        attempt = 0
        while pending:
            attempt += 1
            specs = []
            for bkey, bjobs in pending.items():
                ck_root = bucket_ckpt_dir(cfg.ckpt_dir, bkey)
                has_ck = any(
                    checkpoint_files(os.path.join(ck_root, d))
                    for d in (os.listdir(ck_root)
                              if os.path.isdir(ck_root) else [])
                    if d.startswith("tenant-"))
                specs.append({
                    "bkey": bkey,
                    "jobs": [{k: v for k, v in j.items() if k != "path"}
                             for j in bjobs],
                    "ckpt_dir": ck_root,
                    "action": "resume" if has_ck else "run",
                    "out": os.path.join(
                        cfg.work_dir, f"job-{bkey}-{attempt:03d}.json")})
            from ..testing.multiproc import _pkg_root, worker_env
            cmd = [sys.executable, "-c",
                   "from hmsc_tpu.fleet.jobs import batch_worker_main; "
                   "raise SystemExit(batch_worker_main())",
                   "--buckets", json.dumps(specs),
                   "--run", json.dumps(cfg.run_kw)]
            if getattr(cfg, "bucket_rounding", None):
                cmd += ["--rounding", json.dumps(cfg.bucket_rounding)]
            log_path = os.path.join(cfg.work_dir,
                                    f"job-grouped-{attempt:03d}.log")
            # one child span for the whole grouped attempt: every bucket in
            # the sweep shares the worker process, so they share its span
            ctx = self.trace.child()
            with open(log_path, "w") as logf:
                p = subprocess.Popen(cmd, cwd=_pkg_root(),
                                     env=worker_env(trace=ctx),
                                     stdout=logf,
                                     stderr=subprocess.STDOUT)
            for spec in specs:
                self._emit("job_dispatch", bucket=spec["bkey"],
                           attempt=attempt, pid=p.pid,
                           action=spec["action"], grouped=True,
                           n_tenants=len(spec["jobs"]),
                           tenants=[j["name"] for j in spec["jobs"]],
                           span=ctx.span_id, parent=self.trace.span_id)
            try:
                rc = p.wait(timeout=cfg.wall_timeout_s * len(specs))
            except subprocess.TimeoutExpired:
                p.kill()
                rc = p.wait()
            for spec in specs:
                bkey = spec["bkey"]
                rec = None
                if os.path.exists(spec["out"]):
                    try:
                        with open(spec["out"]) as f:
                            rec = json.load(f)
                    except (OSError, ValueError):
                        rec = None
                if rec is None:
                    self._emit("job_exit", bucket=bkey, attempt=attempt,
                               rc=int(rc), outcome=describe(int(rc)))
                    self.attempt_log.append(
                        {"bucket": bkey, "attempt": attempt,
                         "action": spec["action"], "rc": int(rc)})
                    continue
                ok = all(t["ok"] for t in rec.get("tenants", []))
                b_rc = EXIT_OK if ok else EXIT_DIVERGED
                self._emit("job_exit", bucket=bkey, attempt=attempt,
                           rc=b_rc, outcome=describe(b_rc))
                self.attempt_log.append(
                    {"bucket": bkey, "attempt": attempt,
                     "action": spec["action"], "rc": b_rc})
                self._emit_bucket_done(bkey, rec)
                done[bkey] = {"bucket": bkey, "attempts": attempt,
                              "ok": ok, "diverged": not ok, "result": rec}
                del pending[bkey]
            if not pending:
                break
            budget -= 1
            if budget <= 0:
                for bkey in pending:
                    self._emit("job_abort", bucket=bkey,
                               reason="budget-exhausted", attempts=attempt)
                    done[bkey] = {"bucket": bkey, "attempts": attempt,
                                  "ok": False, "diverged": False,
                                  "result": None}
                break
            backoff = min(cfg.backoff_base_s
                          * cfg.backoff_factor ** (attempt - 1),
                          cfg.backoff_max_s)
            self._emit("backoff", bucket="grouped",
                       seconds=round(backoff, 3))
            time.sleep(backoff)
        return [done[b] for b in sorted(done)]

    def run(self, chaos_kill_at=None) -> dict:
        """Run the whole queue: scan, plan, dispatch every bucket.
        Returns the summary dict the CLI prints (with the batch ``report``
        section: per-bucket occupancy and padding waste)."""
        from .supervisor import fleet_events_path
        cfg = self.cfg
        os.makedirs(cfg.work_dir, exist_ok=True)
        os.makedirs(cfg.ckpt_dir, exist_ok=True)
        self.telem.attach_sink(fleet_events_path(cfg.ckpt_dir),
                               truncate=True)
        jobs = scan_jobs(self.jobs_dir)
        tenants = expand_scenarios(jobs)
        buckets = plan_buckets(tenants,
                               getattr(cfg, "bucket_rounding", None))
        self._emit("queue_start", n_jobs=len(jobs),
                   n_tenants=len(tenants), n_buckets=len(buckets),
                   buckets={k: [j["name"] for j in v]
                            for k, v in sorted(buckets.items())})
        if getattr(cfg, "group_buckets", False) and chaos_kill_at is None:
            outcomes = self._run_buckets_grouped(buckets)
        else:
            outcomes = []
            for bkey, bjobs in sorted(buckets.items()):
                outcomes.append(self._run_bucket_supervised(
                    bkey, bjobs, chaos_kill_at=chaos_kill_at))
        report = {"buckets": [], "occupancy": None, "padding_waste": None}
        cr = cp = 0
        for o in outcomes:
            rep = (o["result"] or {}).get("report") or {}
            for b in rep.get("buckets", []):
                report["buckets"].append(b)
                cr += b.get("cells_real", 0)
                cp += b.get("cells_padded", 0)
        if cp:
            report["occupancy"] = round(cr / cp, 4)
            report["padding_waste"] = round(1.0 - cr / cp, 4)
        scenarios, scenario_preds = self._aggregate_scenarios(jobs, outcomes)
        status = queue_status(outcomes)
        summary = {
            "ok": status == "ok",
            "status": status,
            "n_jobs": len(jobs), "n_tenants": len(tenants),
            "n_buckets": len(buckets),
            "bucket_outcomes": [{k: v for k, v in o.items()
                                 if k != "result"} for o in outcomes],
            "tenants_done": sum(
                len((o["result"] or {}).get("tenants", []))
                for o in outcomes),
            "report": report,
            "scenarios": scenarios,
            "wall_s": round(time.monotonic() - self._t0, 3),
        }
        # queue_end stays light: the (ny, ns) CV prediction matrices ride
        # only the returned summary, not the event stream
        self._emit("queue_end", **summary)
        summary["scenario_preds"] = scenario_preds
        return summary

    def _aggregate_scenarios(self, jobs, outcomes):
        """Reduce per-tenant scenario payloads to one comparison record per
        scenario job: CV folds regroup by parent into an aggregate RMSE and
        the serial ``compute_predicted_values``-shaped (ny, ns) posterior-
        mean matrix; waic / gradient pass their verdicts through.  Emits one
        ``scenario_done`` fleet event per scenario."""
        import math
        by_parent: dict[str, dict] = {}
        for o in outcomes:
            for trec in (o["result"] or {}).get("tenants", []):
                sc = trec.get("scenario")
                if not sc:
                    continue
                e = by_parent.setdefault(
                    sc["parent"], {"scenario": sc["parent"], "ok": True,
                                   "_folds": []})
                e["ok"] &= bool(trec["ok"]) and not sc.get("failed")
                if sc.get("failed"):
                    continue
                if sc["kind"] == "cv_fold":
                    e["type"] = "cv"
                    e["nfolds"] = int(sc["nfolds"])
                    e["_folds"].append(sc)
                elif sc["kind"] == "waic":
                    e["type"] = "waic"
                    e["waic"] = sc["waic"]
                elif sc["kind"] == "gradient":
                    e["type"] = "gradient"
                    e["focal"] = sc["focal"]
                    e["ngrid"] = sc["ngrid"]
                    e["grid"] = sc["grid"]
                    e["grid_pred"] = sc["grid_pred"]
        scenarios, preds = [], {}
        for job in jobs:           # job-file order, deterministic
            e = by_parent.get(job["name"])
            if e is None:
                continue
            folds = sorted(e.pop("_folds"), key=lambda s: s["fold"])
            if e.get("type") == "cv":
                sse = sum(s["sse"] for s in folds)
                n = sum(s["n"] for s in folds)
                e["folds_done"] = len(folds)
                e["ok"] &= len(folds) == e["nfolds"]
                e["rmse"] = round(math.sqrt(sse / n), 6) if n else None
                pm = {}
                for s in folds:
                    for i, row in zip(s["val_rows"], s["pred_mean"]):
                        pm[int(i)] = row
                preds[job["name"]] = pm
            elif e.get("type") == "gradient" and "grid_pred" in e:
                preds[job["name"]] = {"grid": e["grid"],
                                      "grid_pred": e["grid_pred"]}
                # one scalar for the comparison report: the mean (over
                # species) response shift across the focal sweep
                lo, hi = e["grid_pred"][0], e["grid_pred"][-1]
                e["pred_span"] = round(
                    sum(h - l for l, h in zip(lo, hi)) / len(lo), 6)
            light = {k: v for k, v in e.items()
                     if k not in _SCENARIO_HEAVY}
            self._emit("scenario_done", **light)
            scenarios.append(light)
        return scenarios, preds


if __name__ == "__main__":
    raise SystemExit(batch_worker_main())
