"""Elastic fleet supervisor: spawn, watch, heal, shrink, grow.

The supervisor composes machinery earlier PRs built separately — atomic
append-layout checkpoints, the coordinated preemption unwind, resume
re-sharding across process counts, rank-tagged telemetry — into an actual
operator for preemptible capacity:

- it spawns R worker ranks (the :mod:`hmsc_tpu.testing.multiproc` worker,
  one subprocess per rank, ``FileCoordinator`` over a per-attempt
  sentinel directory);
- it watches **liveness** two ways: process exit codes
  (:mod:`hmsc_tpu.exit_codes`) and per-rank heartbeat files — a rank that
  is alive but heartbeat-silent past ``heartbeat_timeout_s`` is presumed
  wedged and SIGKILLed (its peers then unwind with a clean
  ``CoordinationError`` at their next collective);
- any failure ends the attempt; the next attempt **resumes** from the
  last committed manifest after an exponential backoff (or restarts
  fresh when the fleet died before its first commit), so the core
  invariant holds by construction: *zero committed draws lost, ever* —
  a restart can only re-run the uncommitted tail;
- **degradation**: each rank slot has a restart budget; when a slot
  exhausts it, the fleet shrinks to the next divisor of ``n_chains``
  (resume re-shards the chains), and after ``grow_after_attempts``
  attempts at reduced size the recovered capacity grows it back;
- every decision is recorded as a ``kind="fleet"`` event in
  ``fleet-events.jsonl`` next to the run's ``events-p<rank>.jsonl``
  streams, rendered by ``python -m hmsc_tpu report`` as the fleet
  timeline.

Chaos: a :class:`~hmsc_tpu.testing.chaos.ChaosPlan` injects scripted
faults — armed worker flags (progress-triggered SIGKILL/SIGTERM,
heartbeat-freeze, disk-full) and wall-clock Poisson kills — which is how
``benchmarks/bench_chaos.py`` and the ``chaos``-marked tests prove the
invariant end-to-end.
"""

from __future__ import annotations

import os
import signal
import subprocess
import time

from ..exit_codes import (EXIT_CKPT_CORRUPT, EXIT_COORDINATION,
                          EXIT_DIVERGED, EXIT_OK, EXIT_PREEMPTED, describe)

__all__ = ["FleetSupervisor", "fleet_events_path", "FLEET_EVENTS_FILE",
           "log_tail"]

FLEET_EVENTS_FILE = "fleet-events.jsonl"


def fleet_events_path(run_dir: str) -> str:
    """The supervisor's event stream, next to the run's per-rank streams
    (``report`` renders it as the fleet timeline).  The autopilot
    (:mod:`hmsc_tpu.pipeline`) appends its ``kind="pipeline"`` decisions
    to the SAME file, so one stream tells a run's whole operational
    story."""
    return os.path.join(os.fspath(run_dir), FLEET_EVENTS_FILE)


def log_tail(path: str, nbytes: int = 1500) -> str:
    """Last ``nbytes`` of a worker log file (best-effort) — attached to
    failure events so the timeline carries the evidence, not a pointer."""
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            f.seek(max(0, f.tell() - nbytes))
            return f.read().decode(errors="replace")
    except OSError:
        return ""


class FleetSupervisor:
    """Run one fleet to completion (see module docstring).

    ``chaos`` is an optional :class:`~hmsc_tpu.testing.chaos.ChaosPlan`;
    armed events become worker spawn flags, wall-clock events are
    delivered by the watch loop.  :meth:`run` returns the summary dict the
    CLI prints; ``attempt_log`` keeps the per-attempt outcomes for tests.
    """

    def __init__(self, config, *, chaos=None):
        from ..obs import RunTelemetry
        from ..obs.trace import inherit_or_mint
        self.cfg = config
        self.chaos = chaos
        self.telem = RunTelemetry(proc=0)
        # a fleet run is a top-level entry point: every fleet event and
        # every spawned rank's stream joins this trace (obs.trace)
        self.trace = inherit_or_mint()
        self.telem.set_trace(self.trace)
        self.hub = None               # in-process MetricsHub (run() attaches)
        self.attempt_log: list = []
        self._t0 = time.monotonic()

    # -- event plumbing ----------------------------------------------------

    def _emit(self, name: str, **fields) -> None:
        self.telem.emit("fleet", name, **fields)
        self.telem.flush()            # the stream must be tailable live

    # -- spawn / watch one attempt -----------------------------------------

    def _spawn(self, rank: int, nprocs: int, attempt: int, action: str,
               coord_dir: str, hb_dir: str):
        from ..testing.multiproc import _pkg_root, worker_cmd, worker_env
        cfg = self.cfg
        extra = []
        if self.chaos is not None:
            extra += self.chaos.arm_flags(rank, attempt)
        if cfg.pin_cpus:
            extra += ["--pin-cpu", str(rank % (os.cpu_count() or 1))]
        out = os.path.join(cfg.work_dir, f"out-{attempt:03d}-r{rank}.json")
        cmd = worker_cmd(
            rank, nprocs, coord_dir=coord_dir, ckpt_dir=cfg.ckpt_dir,
            model_kw=cfg.model_kw,
            # resume attempts take the stored run configuration from the
            # checkpoint — only the first attempt passes run_kw through
            run_kw=(cfg.run_kw if action == "run" else {}),
            action=action, timeout_s=cfg.coord_timeout_s, out=out,
            heartbeat_dir=hb_dir,
            heartbeat_interval_s=cfg.heartbeat_interval_s,
            extra_args=extra)
        log_path = os.path.join(cfg.work_dir,
                                f"worker-{attempt:03d}-r{rank}.log")
        # worker output goes to a file, not a pipe: a full pipe would wedge
        # a healthy worker mid-run while its heartbeat keeps beating
        logf = open(log_path, "w")
        # each rank inherits the fleet trace as its parent span — the
        # rank's events-p<r>.jsonl stream joins the fleet timeline
        p = subprocess.Popen(cmd, cwd=_pkg_root(),
                             env=worker_env(trace=self.trace),
                             stdout=logf, stderr=subprocess.STDOUT)
        logf.close()                  # the child holds its own descriptor
        self._emit("spawn", attempt=attempt, rank=rank, pid=p.pid,
                   nprocs=nprocs, action=action, chaos_flags=extra or None)
        return p, log_path

    def _log_tail(self, path: str, nbytes: int = 1500) -> str:
        return log_tail(path, nbytes)

    def _attempt(self, attempt: int, nprocs: int, action: str) -> dict:
        cfg = self.cfg
        coord_dir = os.path.join(cfg.work_dir, f"coord-{attempt:03d}")
        hb_dir = os.path.join(cfg.work_dir, "hb")
        os.makedirs(coord_dir, exist_ok=True)
        os.makedirs(hb_dir, exist_ok=True)
        from ..utils.coordination import heartbeat_path, read_heartbeats
        # a SIGKILLed rank leaves its old heartbeat file behind; spawning
        # over it would read as instantly-silent — sweep before spawn
        for r in range(nprocs):
            try:
                os.unlink(heartbeat_path(hb_dir, r))
            except OSError:
                pass
        self._emit("attempt_start", attempt=attempt, nprocs=nprocs,
                   action=action)
        procs, logs = {}, {}
        for r in range(nprocs):
            procs[r], logs[r] = self._spawn(r, nprocs, attempt, action,
                                            coord_dir, hb_dir)

        t_att = time.monotonic()
        exits: dict = {}
        hb_killed: list = []
        timed_out = False
        while procs:
            for r, p in list(procs.items()):
                rc = p.poll()
                if rc is not None:
                    exits[r] = int(rc)
                    self._emit("exit", attempt=attempt, rank=r, rc=int(rc),
                               outcome=describe(rc),
                               log_tail=(self._log_tail(logs[r])
                                         if rc not in (EXIT_OK,
                                                       EXIT_PREEMPTED)
                                         else None))
                    del procs[r]
            if not procs:
                break
            if self.chaos is not None:
                for ev in self.chaos.due_signals(time.monotonic() - self._t0):
                    p = procs.get(ev.rank)
                    live = p is not None and p.poll() is None
                    self._emit("chaos", attempt=attempt, action=ev.action,
                               rank=ev.rank, at_s=ev.at_s, delivered=live)
                    if live:
                        os.kill(p.pid, signal.SIGKILL
                                if ev.action == "sigkill" else signal.SIGTERM)
            hb = read_heartbeats(hb_dir)
            elapsed = time.monotonic() - t_att
            for r, p in list(procs.items()):
                if r in hb_killed:    # already killed; waiting on the exit
                    continue
                rec = hb.get(r)
                if rec is None:
                    silent = elapsed > cfg.startup_grace_s
                    age = None
                else:
                    age = rec["age_s"]
                    silent = age > cfg.heartbeat_timeout_s
                if silent:
                    self._emit("heartbeat_silent", attempt=attempt, rank=r,
                               age_s=age, pid=p.pid)
                    hb_killed.append(r)
                    p.kill()          # the exit is collected next poll
            if elapsed > cfg.wall_timeout_s:
                timed_out = True
                self._emit("attempt_timeout", attempt=attempt,
                           elapsed_s=round(elapsed, 1))
                for p in procs.values():
                    p.kill()
            if self.hub is not None:
                # live SLO check each liveness tick: alerts land in this
                # stream (kind="alert") next to the decisions they motivate
                self.hub.pump()
            time.sleep(cfg.poll_s)
        rec = {"attempt": attempt, "nprocs": nprocs, "action": action,
               "exits": exits, "hb_killed": hb_killed,
               "timed_out": timed_out}
        self.attempt_log.append(rec)
        return rec

    # -- the supervision loop ----------------------------------------------

    def run(self) -> dict:
        cfg = self.cfg
        os.makedirs(cfg.work_dir, exist_ok=True)
        os.makedirs(cfg.ckpt_dir, exist_ok=True)
        self.telem.attach_sink(fleet_events_path(cfg.ckpt_dir),
                               truncate=True)
        # in-process metrics hub over the run directory: the supervisor
        # evaluates the SLO rules against its own fleet while it runs
        from ..obs.hub import MetricsHub
        self.hub = MetricsHub(cfg.ckpt_dir, alert_telemetry=self.telem)
        ladder = cfg.ladder()
        nprocs = ladder[0]
        budgets = {r: int(cfg.restart_budget) for r in range(ladder[0])}
        consecutive_fail = 0
        attempts = restarts = shrinks = grows = 0
        attempts_at_reduced = 0
        status = "unknown"
        self._emit("fleet_start", config=cfg.to_dict(), ladder=ladder,
                   chaos=(self.chaos.summary()
                          if self.chaos is not None else None))
        while True:
            if attempts >= int(cfg.max_attempts):
                status = "max-attempts"
                break
            # recovered capacity grows the fleet back one ladder step
            if nprocs < ladder[0] \
                    and attempts_at_reduced >= int(cfg.grow_after_attempts):
                bigger = [x for x in ladder if x > nprocs]
                grown = bigger[-1]    # one step up, not straight to max
                for r in range(nprocs, grown):
                    budgets[r] = int(cfg.restart_budget)
                self._emit("grow", from_procs=nprocs, to_procs=grown)
                grows += 1
                nprocs = grown
                attempts_at_reduced = 0
            attempts += 1
            if nprocs < ladder[0]:
                attempts_at_reduced += 1
            # resume only when a committed snapshot exists: a fleet killed
            # before its FIRST commit has nothing to resume (the workers
            # would abort with exit 78), so the retry is a fresh run — the
            # zero-loss invariant holds trivially, nothing was committed
            from ..utils.checkpoint import checkpoint_files
            action = "resume" if checkpoint_files(cfg.ckpt_dir) else "run"
            rec = self._attempt(attempts, nprocs, action)
            exits = rec["exits"]
            if all(rc == EXIT_OK for rc in exits.values()):
                status = "ok"
                break
            if any(rc == EXIT_DIVERGED for rc in exits.values()):
                # a deterministic blow-up would recur on restart: stop and
                # surface it instead of burning the restart budget
                status = "diverged"
                break
            if any(rc == EXIT_CKPT_CORRUPT for rc in exits.values()):
                status = "checkpoint-corrupt"
                break
            consecutive_fail += 1
            restarts += 1
            # blame the ranks that actually failed; EXIT_COORDINATION is
            # collateral (the survivor of a dead peer), EXIT_OK finished
            culprits = sorted(
                set(r for r, rc in exits.items()
                    if rc not in (EXIT_OK, EXIT_COORDINATION))
                | set(rec["hb_killed"]))
            for r in culprits:
                budgets[r] = budgets.get(r, int(cfg.restart_budget)) - 1
            exhausted = [r for r in range(nprocs) if budgets.get(r, 1) <= 0]
            if exhausted:
                smaller = [x for x in ladder if x < nprocs]
                if not smaller:
                    status = "budget-exhausted"
                    self._emit("abort", reason="budget-exhausted",
                               ranks=exhausted)
                    break
                self._emit("shrink", from_procs=nprocs,
                           to_procs=smaller[0], exhausted_ranks=exhausted)
                shrinks += 1
                nprocs = smaller[0]
                attempts_at_reduced = 0
                # the shrink IS the response to the exhaustion: the reduced
                # fleet starts with fresh budgets (a still-zero slot would
                # otherwise trigger another shrink on the next unrelated
                # failure)
                for r in range(nprocs):
                    budgets[r] = int(cfg.restart_budget)
            backoff = min(cfg.backoff_base_s
                          * cfg.backoff_factor ** (consecutive_fail - 1),
                          cfg.backoff_max_s)
            self._emit("backoff", seconds=round(backoff, 3),
                       consecutive_failures=consecutive_fail,
                       culprits=culprits, budgets=dict(budgets))
            time.sleep(backoff)
        ck = self._verify_checkpoint()
        summary = {
            "ok": status == "ok" and ck.get("valid", False)
            and int(ck.get("samples_done", -1)) >= cfg.samples,
            "status": status,
            "attempts": attempts, "restarts": restarts,
            "shrinks": shrinks, "grows": grows,
            "fleet_size": {"initial": ladder[0], "final": nprocs},
            "budgets": {str(r): b for r, b in sorted(budgets.items())},
            "target_samples": cfg.samples,
            "checkpoint": ck,
            "draws_lost": (max(0, cfg.samples - int(ck["samples_done"]))
                           if ck.get("valid") else None),
            "wall_s": round(time.monotonic() - self._t0, 3),
        }
        self._emit("fleet_end", **summary)
        return summary

    def _verify_checkpoint(self) -> dict:
        """Load + checksum-verify the newest committed manifest — the
        zero-committed-draws-lost evidence the summary carries."""
        from ..testing.multiproc import build_worker_model
        from ..utils.checkpoint import (CheckpointError,
                                        latest_valid_checkpoint)
        try:
            hM = build_worker_model(**self.cfg.model_kw)
            ck = latest_valid_checkpoint(self.cfg.ckpt_dir, hM)
            return {"valid": True,
                    "manifest": os.path.basename(ck.path),
                    "samples_done": int(ck.post.samples),
                    "n_chains": int(ck.post.n_chains)}
        except (CheckpointError, ValueError, OSError) as e:
            return {"valid": False, "error": f"{type(e).__name__}: {e}"}
