from .rand import (truncated_normal, truncated_normal_onesided, polya_gamma,
                   wishart, mvn_from_prec_chol, categorical_logits)
from .linalg import chol_spd, solve_from_chol

__all__ = ["truncated_normal", "truncated_normal_onesided", "polya_gamma",
           "wishart", "mvn_from_prec_chol", "categorical_logits", "chol_spd",
           "solve_from_chol"]
