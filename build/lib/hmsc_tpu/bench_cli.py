"""Installed-package throughput probe (``hmsc-tpu-bench`` console script).

Measures steady-state posterior samples/sec of the blocked-Gibbs engine on
whatever accelerator JAX finds (compile excluded, best-of-3 windows) and
prints one JSON line.  The repo-level ``bench.py`` harness additionally runs
the reference-style NumPy baseline for a measured ``vs_baseline`` ratio; from
an installed wheel only the package itself is available, so the ratio is
reported as ``null`` here.
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def _model(ny, ns, nf, seed=66):
    import pandas as pd

    from .model import Hmsc
    from .random_level import HmscRandomLevel, set_priors_random_level

    rng = np.random.default_rng(seed)
    X = np.column_stack([np.ones(ny), rng.standard_normal(ny)])
    Y = ((X @ (rng.standard_normal((2, ns)) * 0.5)
          + rng.standard_normal((ny, 2)) @ (rng.standard_normal((2, ns)) * 0.7)
          + rng.standard_normal((ny, ns))) > 0).astype(float)
    study = pd.DataFrame({"sample": [f"s{i:04d}" for i in range(ny)]})
    rL = HmscRandomLevel(units=study["sample"])
    set_priors_random_level(rL, nf_max=nf, nf_min=2)
    return Hmsc(Y=Y, X=X, study_design=study, ran_levels={"sample": rL},
                distr="probit", x_scale=False)


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="hmsc-tpu sampling-throughput probe")
    parser.add_argument("--ny", type=int, default=200)
    parser.add_argument("--ns", type=int, default=100)
    parser.add_argument("--nf", type=int, default=4)
    parser.add_argument("--samples", type=int, default=200)
    parser.add_argument("--chains", type=int, default=4)
    args = parser.parse_args(argv)

    import jax

    from .mcmc.sampler import sample_mcmc

    hM = _model(args.ny, args.ns, args.nf)
    kw = dict(samples=args.samples, transient=10, n_chains=args.chains,
              align_post=False, nf_cap=args.nf)
    sample_mcmc(hM, seed=0, **kw)               # warm-up: compile
    t = np.inf
    for rep in range(3):
        t0 = time.time()
        post = sample_mcmc(hM, seed=1 + rep, **kw)
        t = min(t, time.time() - t0)
        assert np.all(np.isfinite(post["Beta"]))
    print(json.dumps({
        "metric": f"posterior samples/sec ({args.ns}-species probit JSDM, "
                  f"{args.chains} chains, {jax.devices()[0].platform})",
        "value": round(args.chains * args.samples / t, 2),
        "unit": "samples/sec",
        "vs_baseline": None,
    }))


if __name__ == "__main__":
    main()
