"""Visualization layer (L6, reference ``R/plotBeta.R:59-264``,
``R/plotGamma.R:50-180``, ``R/plotGradient.R:63-210``,
``R/plotVariancePartitioning.R:21-41``, ``R/biPlot.R:26-59``).

Matplotlib-level presentation over the L4/L5 outputs; pure host-side.  Each
function returns the matplotlib ``Axes`` so callers can restyle or save.
``plot_beta``/``plot_gamma`` support the reference's three display modes:
posterior mean, support (P(>0)), and sign-thresholded mean.
"""

from __future__ import annotations

import numpy as np

__all__ = ["plot_beta", "plot_gamma", "plot_gradient",
           "plot_variance_partitioning", "bi_plot"]


def _ax(ax):
    if ax is not None:
        return ax
    import matplotlib.pyplot as plt
    _, ax = plt.subplots()
    return ax


def _mode_matrix(est, plot_type, support_level):
    """The displayed matrix for the reference's three plot modes."""
    mean = est["mean"]
    if plot_type == "Mean":
        return mean
    if plot_type == "Support":
        return np.where(est["support"] > support_level, est["support"],
                        np.where(est["supportNeg"] > support_level,
                                 -est["supportNeg"], 0.0))
    if plot_type == "Sign":
        sig = (est["support"] > support_level) \
            | (est["supportNeg"] > support_level)
        return np.where(sig, np.sign(mean), 0.0)
    raise ValueError("plotType must be 'Mean', 'Support' or 'Sign'")


def _support_plot(est, row_names, col_names, plot_type, support_level, ax,
                  title):
    ax = _ax(ax)
    M = _mode_matrix(est, plot_type, support_level)
    vmax = np.max(np.abs(M)) or 1.0
    im = ax.imshow(M, cmap="RdBu_r", vmin=-vmax, vmax=vmax, aspect="auto")
    ax.set_xticks(range(len(col_names)))
    ax.set_xticklabels(col_names, rotation=90, fontsize=7)
    ax.set_yticks(range(len(row_names)))
    ax.set_yticklabels(row_names, fontsize=7)
    ax.set_title(title)
    ax.figure.colorbar(im, ax=ax, shrink=0.8)
    return ax


def _draw_c_dendrogram(ax_t, C):
    """UPGMA dendrogram of the phylogenetic correlation matrix; returns the
    bottom-to-top species order with leaf h at y = 5 + 10 h."""
    from scipy.cluster import hierarchy
    from scipy.spatial.distance import squareform

    D = 1.0 - np.asarray(C, dtype=float)
    D = np.clip((D + D.T) / 2.0, 0.0, None)
    np.fill_diagonal(D, 0.0)
    Z = hierarchy.linkage(squareform(D, checks=False), method="average")
    dn = hierarchy.dendrogram(Z, orientation="left", ax=ax_t, no_labels=True,
                              color_threshold=0,
                              above_threshold_color="#555555")
    return dn["leaves"]


def _draw_phylogram(ax_t, newick, sp_names):
    """The supplied tree itself, as the reference's ``ape::plot.phylo`` panel
    (``plotBeta.R:59-264``): x = root-to-node distance (real branch lengths),
    leaf h at y = 5 + 10 h (the shared row coordinate), internal nodes at the
    mean of their children.  Trees covering more species than the model are
    pruned to the modeled set.  Returns the bottom-to-top species order."""
    from .utils.phylo import parse_newick, prune_parsed

    sp = [str(s) for s in sp_names]
    children, lengths, names = prune_parsed(*parse_newick(newick), sp)
    n = len(children)
    depth = np.zeros(n)
    for v in range(n):                       # parents precede children
        for c in children[v]:
            depth[c] = depth[v] + lengths[c]
    # leaf order: DFS in Newick child order, bottom-to-top
    leaves, stack = [], [0]
    while stack:
        v = stack.pop()
        if not children[v]:
            leaves.append(v)
        else:
            stack.extend(reversed(children[v]))
    y = np.zeros(n)
    for i, v in enumerate(leaves):
        y[v] = 5.0 + 10.0 * i
    for v in range(n - 1, -1, -1):           # children before parents
        if children[v]:
            y[v] = np.mean([y[c] for c in children[v]])
    for v in range(n):
        for c in children[v]:
            ax_t.plot([depth[v], depth[c]], [y[c], y[c]],
                      color="#555555", lw=1.0)
        if children[v]:
            ys = [y[c] for c in children[v]]
            ax_t.plot([depth[v], depth[v]], [min(ys), max(ys)],
                      color="#555555", lw=1.0)
    ax_t.set_xlim(-0.02 * max(depth.max(), 1e-12), depth.max() * 1.02)
    pos = {name: i for i, name in enumerate(sp)}
    return [pos[names[v]] for v in leaves]


def plot_beta(post, plot_type: str = "Support", support_level: float = 0.89,
              ax=None, *, plot_tree: bool = False):
    """Heatmap of species' environmental responses Beta (covariates x
    species), reference ``plotBeta.R``.

    ``plot_tree=True`` draws the phylogeny side panel (reference
    ``plotBeta.R:59-264``, which renders the ``ape`` tree): species move to
    the y-axis with the tree drawn left of the heatmap, leaves aligned to
    the rows.  A model built with ``phylo_tree=`` draws the actual supplied
    topology and branch lengths (pruned to the modeled species); a model
    built with only ``C`` falls back to an average-linkage dendrogram of
    the correlation matrix (distance ``1 - C``) — a reconstruction that is
    exact for ultrametric trees only.
    """
    hM = post.hM
    est = post.get_post_estimate("Beta")
    if not plot_tree:
        return _support_plot(est, hM.cov_names, hM.sp_names, plot_type,
                             support_level, ax, "Beta")
    if hM.C is None:
        raise ValueError(
            "Hmsc.plotBeta: plot_tree requires a model with a phylogenetic "
            "correlation matrix C")
    if ax is not None:
        raise ValueError(
            "Hmsc.plotBeta: plot_tree draws its own two-panel figure; "
            "the ax argument cannot be combined with it")
    import matplotlib.pyplot as plt

    fig, (ax_t, ax_h) = plt.subplots(
        1, 2, figsize=(9, max(4, 0.3 * hM.ns + 2)),
        gridspec_kw={"width_ratios": [1, 3], "wspace": 0.02})
    if getattr(hM, "phylo_tree", None) is not None:
        order = _draw_phylogram(ax_t, hM.phylo_tree, hM.sp_names)
    else:
        order = _draw_c_dendrogram(ax_t, hM.C)
    M = _mode_matrix(est, plot_type, support_level)[:, order].T  # (ns, nc)
    vmax = np.max(np.abs(M)) or 1.0
    # dendrogram leaf h sits at y = 5 + 10 h; the extent puts heatmap row h
    # exactly there so the panels align
    im = ax_h.imshow(M, cmap="RdBu_r", vmin=-vmax, vmax=vmax, aspect="auto",
                     origin="lower", extent=(-0.5, M.shape[1] - 0.5,
                                             0, 10 * hM.ns))
    ax_t.set_ylim(0, 10 * hM.ns)
    ax_t.set_axis_off()
    ax_h.set_yticks(5 + 10 * np.arange(hM.ns))
    ax_h.set_yticklabels([hM.sp_names[j] for j in order], fontsize=7)
    ax_h.set_xticks(range(len(hM.cov_names)))
    ax_h.set_xticklabels(hM.cov_names, rotation=90, fontsize=7)
    ax_h.set_title("Beta")
    fig.colorbar(im, ax=ax_h, shrink=0.8)
    return ax_h


def plot_gamma(post, plot_type: str = "Support", support_level: float = 0.89,
               ax=None):
    """Heatmap of trait effects Gamma (covariates x traits), reference
    ``plotGamma.R``."""
    hM = post.hM
    est = post.get_post_estimate("Gamma")
    return _support_plot(est, hM.cov_names, hM.tr_names, plot_type,
                         support_level, ax, "Gamma")


def plot_gradient(post, gradient, pred=None, measure: str = "S", index: int = 0,
                  q=(0.25, 0.5, 0.75), show_data: bool = True, ax=None,
                  seed: int = 0):
    """Prediction along an environmental gradient with credible ribbons
    (reference ``plotGradient.R``): ``measure``='S' species richness, 'Y'
    one species (``index``), 'T' community-weighted mean trait (``index``)."""
    from .predict import predict as _predict

    hM = post.hM
    if pred is None:
        pred = _predict(post, gradient=gradient, expected=True, seed=seed)
    xx = np.asarray(gradient["XDataNew"].iloc[:, 0], dtype=float)
    if measure == "S":
        stat = pred.sum(axis=2)                      # (n, ngrid)
        label = "Summed response (richness)"
    elif measure == "Y":
        stat = pred[:, :, index]
        label = f"{hM.sp_names[index]}"
    elif measure == "T":
        tw = pred @ hM.Tr[:, index]
        stat = tw / np.maximum(pred.sum(axis=2), 1e-12)
        label = f"CWM {hM.tr_names[index]}"
    else:
        raise ValueError("measure must be 'S', 'Y' or 'T'")
    lo, med, hi = np.quantile(stat, q, axis=0)
    ax = _ax(ax)
    ax.fill_between(xx, lo, hi, alpha=0.3, color="#4477aa", lw=0)
    ax.plot(xx, med, color="#4477aa")
    ax.set_xlabel(str(gradient["XDataNew"].columns[0]))
    ax.set_ylabel(label)
    if show_data and measure == "S" and hM.x_data is not None:
        try:
            v = np.asarray(hM.x_data[gradient["XDataNew"].columns[0]], float)
            ax.plot(v, np.nansum(hM.Y, axis=1), ".", color="#666666",
                    markersize=3)
        except Exception:
            pass
    return ax


def plot_variance_partitioning(post, vp=None, ax=None, cmap: str = "tab20"):
    """Stacked per-species bars of the variance shares (reference
    ``plotVariancePartitioning.R``)."""
    from .post.metrics import compute_variance_partitioning

    hM = post.hM
    if vp is None:
        vp = compute_variance_partitioning(post)
    vals = vp["vals"]
    ax = _ax(ax)
    import matplotlib.pyplot as plt

    colors = plt.get_cmap(cmap)(np.linspace(0, 1, vals.shape[0]))
    bottom = np.zeros(vals.shape[1])
    xs = np.arange(vals.shape[1])
    means = vals.mean(axis=1)
    for i in range(vals.shape[0]):
        ax.bar(xs, vals[i], bottom=bottom, color=colors[i],
               label=f"{vp['names'][i]} (mean = {means[i]:.2f})")
        bottom += vals[i]
    ax.set_xticks(xs)
    ax.set_xticklabels(hM.sp_names, rotation=90, fontsize=7)
    ax.set_ylabel("Variance proportion")
    ax.legend(fontsize=6, loc="upper right")
    return ax


def bi_plot(post, r: int = 0, factors=(0, 1), color_var=None, ax=None):
    """Ordination of sites (posterior-mean Eta) against species loadings
    (posterior-mean Lambda) for one random level (reference ``biPlot.R``)."""
    hM = post.hM
    eta = post.get_post_estimate("Eta", r=r)["mean"]       # (np, nf)
    lam = post.get_post_estimate("Lambda", r=r)["mean"]    # (nf, ns[, ncr])
    lam = lam[..., 0] if lam.ndim == 3 else lam
    f1, f2 = factors
    ax = _ax(ax)
    c = None
    if color_var is not None and hM.x_data is not None:
        v = np.asarray(hM.x_data[color_var], dtype=float)
        if len(v) == eta.shape[0]:           # one row per unit already
            c = v
        elif len(v) == hM.ny:                # map rows -> first row per unit
            first_row = np.zeros(eta.shape[0], dtype=int)
            first_row[hM.Pi[::-1, r]] = np.arange(hM.ny - 1, -1, -1)
            c = v[first_row]
    kw = {"c": c, "cmap": "viridis"} if c is not None else {}
    ax.scatter(eta[:, f1], eta[:, f2], s=12, label="sites", **kw)
    scale = (np.abs(eta[:, [f1, f2]]).max() /
             max(np.abs(lam[[f1, f2]]).max(), 1e-12))
    for j in range(hM.ns):
        ax.annotate(hM.sp_names[j], (lam[f1, j] * scale, lam[f2, j] * scale),
                    color="#bb3333", fontsize=8)
    ax.set_xlabel(f"Latent factor {f1 + 1}")
    ax.set_ylabel(f"Latent factor {f2 + 1}")
    return ax
