"""Simulated fixtures.

``make_td`` regenerates the reference's bundled TD dataset in spirit
(reference ``data-raw/simulateTestData.R:1-71``): a small probit JSDM with a
phylogeny, two traits (one continuous, one categorical), one continuous + one
categorical covariate, and two random levels — an unstructured per-sample
level and a spatial per-plot level.  ``simulate_jsdm`` is the general-purpose
generator used by the recovery tests and benchmarks (vignette-2/3 style).
"""

from __future__ import annotations

import numpy as np
import pandas as pd

__all__ = ["make_td", "simulate_jsdm", "random_coalescent_corr"]


def random_coalescent_corr(ns: int, rng: np.random.Generator) -> np.ndarray:
    """A valid Brownian-motion correlation matrix from a random binary
    coalescent-style tree (stand-in for ``ape::rcoal`` + ``vcv.phylo``)."""
    # random sequential coalescence with exponential waiting times
    nodes = [{"tips": (i,), "h": 0.0} for i in range(ns)]
    t = 0.0
    shared = np.zeros((ns, ns))
    k = ns
    while k > 1:
        t += rng.exponential(1.0 / (k * (k - 1) / 2))
        i, j = rng.choice(k, size=2, replace=False)
        a, b = nodes[min(i, j)], nodes[max(i, j)]
        merged = {"tips": a["tips"] + b["tips"], "h": t}
        for p in a["tips"]:
            for q in b["tips"]:
                shared[p, q] = shared[q, p] = t
        nodes = [n for n in nodes if n is not a and n is not b] + [merged]
        k -= 1
    total = t
    C = np.where(np.eye(ns) > 0, total, total - shared) / total
    # shared path length from root = total - coalescence time
    np.fill_diagonal(C, 1.0)
    return C


def make_td(seed: int = 66):
    """TD-like fixture: 4 species x 50 units, 10 spatial plots, probit."""
    from ..model import Hmsc
    from ..random_level import HmscRandomLevel, set_priors_random_level

    rng = np.random.default_rng(seed)
    ns, units, plots = 4, 50, 10

    X = pd.DataFrame({
        "x1": rng.standard_normal(units),
        "x2": pd.Categorical(["o"] * (units // 2) + ["c"] * (units // 2)),
    })
    C = random_coalescent_corr(ns, rng)
    t1 = np.linalg.cholesky(C + 1e-9 * np.eye(ns)) @ rng.standard_normal(ns)
    Tr = pd.DataFrame({"T1": t1,
                       "T2": pd.Categorical(["A", "B", "B", "A"])})

    gamma = np.array([[-2.0, 2.0], [-1.0, 1.0]])
    TrM = np.column_stack([np.ones(ns), t1])
    mu = gamma @ TrM.T                                   # (2, ns)
    beta = mu + np.linalg.cholesky(C + 1e-9 * np.eye(ns)).dot(
        rng.standard_normal((ns, 2))).T
    Xm = np.column_stack([np.ones(units), X["x1"].to_numpy()])
    Lf = Xm @ beta

    plot_of = rng.integers(0, plots, units)
    xy = rng.uniform(size=(plots, 2))
    dd = xy[:, None, :] - xy[None, :, :]
    Sig = 4.0 * np.exp(-np.sqrt((dd**2).sum(-1)) / 0.35)
    eta_plot = np.linalg.cholesky(Sig + 1e-9 * np.eye(plots)) @ rng.standard_normal(plots)
    lam = np.array([-2.0, 2.0, 1.5, 0.0])
    Lr = eta_plot[plot_of][:, None] * lam[None, :]

    Y = ((Lf + Lr + rng.standard_normal((units, ns))) > 0).astype(float)

    study = pd.DataFrame({
        "sample": [f"s{i+1:02d}" for i in range(units)],
        "plot": [f"p{p+1:02d}" for p in plot_of],
    })
    xy_df = pd.DataFrame(xy, index=[f"p{i+1:02d}" for i in range(plots)],
                         columns=["x", "y"])
    rL_plot = HmscRandomLevel(s_data=xy_df)
    rL_samp = HmscRandomLevel(units=study["sample"])
    set_priors_random_level(rL_plot, nf_max=2, nf_min=2)
    set_priors_random_level(rL_samp, nf_max=2, nf_min=2)

    m = Hmsc(Y=Y, x_data=X, x_formula="~x1+x2",
             tr_data=Tr, tr_formula="~T1+T2", C=C,
             study_design=study,
             ran_levels={"sample": rL_samp, "plot": rL_plot},
             distr="probit")
    return {"m": m, "Y": Y, "X": X, "Tr": Tr, "C": C, "beta": beta,
            "gamma": gamma, "xy": xy, "study": study,
            "rL_plot": rL_plot, "rL_samp": rL_samp}


def simulate_jsdm(ny=200, ns=30, nc=3, rng=None, distr="probit",
                  n_factors=2, sigma=1.0, beta_sd=1.0, with_traits=False,
                  nt=2, with_phylo=False, rho=0.6, missing=0.0):
    """General JSDM simulator with known parameters for recovery tests."""
    rng = rng or np.random.default_rng(0)
    X = np.column_stack([np.ones(ny), rng.standard_normal((ny, nc - 1))])
    if with_phylo:
        C = random_coalescent_corr(ns, rng)
        Q = rho * C + (1 - rho) * np.eye(ns)
    else:
        C, Q = None, np.eye(ns)
    if with_traits:
        Tr = np.column_stack([np.ones(ns), rng.standard_normal((ns, nt - 1))])
        Gamma = rng.standard_normal((nc, nt))
        Mu = Gamma @ Tr.T
    else:
        Tr, Gamma = None, None
        Mu = np.zeros((nc, ns))
    sqQ = np.linalg.cholesky(Q + 1e-9 * np.eye(ns))
    Beta = Mu + beta_sd * rng.standard_normal((nc, ns)) @ sqQ.T
    L = X @ Beta
    Eta = rng.standard_normal((ny, n_factors))
    Lambda = rng.standard_normal((n_factors, ns)) * (0.8 ** np.arange(n_factors))[:, None]
    L = L + Eta @ Lambda
    Zn = L + np.sqrt(sigma) * rng.standard_normal((ny, ns))
    if distr == "probit":
        Y = (Zn > 0).astype(float)
    elif distr == "normal":
        Y = Zn
    else:  # poisson / lognormal poisson
        Y = rng.poisson(np.exp(np.clip(Zn, -10, 6))).astype(float)
    if missing > 0:
        drop = rng.uniform(size=Y.shape) < missing
        Y = np.where(drop, np.nan, Y)
    return {"Y": Y, "X": X, "Beta": Beta, "Lambda": Lambda, "Eta": Eta,
            "Tr": Tr, "Gamma": Gamma, "C": C}
