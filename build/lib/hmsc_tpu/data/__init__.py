from .td import make_td, random_coalescent_corr, simulate_jsdm

__all__ = ["make_td", "random_coalescent_corr", "simulate_jsdm"]
