"""Direct prior sampling (reference ``R/samplePrior.R:15-145``), used by
``sample_mcmc(from_prior=True)`` and the Geweke prior<->posterior consistency
tests (SURVEY.md §4).  Host-side numpy; spatial Eta draws use the exact GP
covariance W(alpha) rebuilt from the stored distance structures."""

from __future__ import annotations

import numpy as np
from scipy import stats as sps

__all__ = ["sample_prior", "sample_prior_chains"]


def _spatial_prior_eta(hM, lp, r, alpha_idx, np_r, nf, rng):
    rL = hM.ranLevels[r]
    alphas = rL.alphapw[:, 0]
    eta = rng.standard_normal((np_r, nf))
    if lp is None:
        return eta
    if lp.kind == "Full":
        dist = lp.distance
        for h in range(nf):
            a = alphas[alpha_idx[h]]
            W = np.eye(np_r) if a == 0 else np.exp(-dist / a)
            L = np.linalg.cholesky(W + 1e-8 * np.eye(np_r))
            eta[:, h] = L @ rng.standard_normal(np_r)
        return eta
    if lp.kind == "NNGP":
        # sequential Vecchia draw from the *approximate* process the posterior
        # sampler targets (same nn_coef/nn_D factors), not the exact kernel —
        # keeps prior<->posterior Geweke checks consistent
        for h in range(nf):
            g = alpha_idx[h]
            if alphas[g] == 0:
                continue  # W = I: keep the standard-normal column
            coef, D = lp.nn_coef[g], lp.nn_D[g]
            # padded neighbour slots are safe because precompute zeroes their
            # nn_coef entries (precompute.py pad_mask), not because of init order
            col = np.zeros(np_r)
            eps = rng.standard_normal(np_r)
            for i in range(np_r):
                col[i] = coef[i] @ col[lp.nn_idx[i]] + np.sqrt(D[i]) * eps[i]
            eta[:, h] = col
        return eta
    # GPP: covariance of the predictive process = W12 iW22 W21 + diag(dD),
    # reconstructed from the stored grids so prior == posterior target
    for h in range(nf):
        g = alpha_idx[h]
        if alphas[g] == 0:
            continue
        dD = 1.0 / lp.idDg[g]
        W12 = lp.idDW12g[g] * dD[:, None]
        W22 = lp.Fg[g] - W12.T @ (lp.idDg[g][:, None] * W12)
        cov = W12 @ np.linalg.solve(W22 + 1e-8 * np.eye(W22.shape[0]), W12.T)
        cov += np.diag(dD)
        L = np.linalg.cholesky(cov + 1e-8 * np.eye(np_r))
        eta[:, h] = L @ rng.standard_normal(np_r)
    return eta


def sample_prior(hM, spec, data_par, rng: np.random.Generator) -> dict:
    """One draw of all parameters from the prior, in the recorded-sample
    (combineParameters) schema with factor arrays padded to nf_max."""
    from ..model import FIXED_SIGMA2

    nc, nt, ns = hM.nc, hM.nt, hM.ns
    # column-major vec(Gamma), matching update_gamma_v's convention
    Gamma = rng.multivariate_normal(hM.mGamma, hM.UGamma).reshape(
        (nc, nt), order="F")
    V = np.atleast_2d(sps.invwishart.rvs(df=hM.f0, scale=hM.V0, random_state=rng))

    est = hM.distr[:, 1] == 1
    sigma = np.array([FIXED_SIGMA2[int(f)] for f in hM.distr[:, 0]], dtype=float)
    # prior: iSigma ~ Gamma(aSigma, rate bSigma) — the law updateInvSigma's
    # conjugate draw implies.  The reference's samplePrior.R:34 instead draws
    # *sigma* from that gamma, contradicting its own updater (updateInvSigma.R
    # shape aSigma + n/2 on iSigma); the successive-conditional Geweke tier
    # exposes that inconsistency, so we follow the updater.
    sigma[est] = 1.0 / rng.gamma(hM.aSigma[est], 1.0 / hM.bSigma[est])

    if hM.C is None:
        rho_idx = 0
    else:
        rho_idx = rng.choice(hM.rhopw.shape[0], p=hM.rhopw[:, 1] / hM.rhopw[:, 1].sum())

    rec = {}
    Mu = Gamma @ hM.TrScaled.T
    if hM.C is None:
        Beta = Mu + np.linalg.cholesky(V) @ rng.standard_normal((nc, ns))
    else:
        e = data_par.Qeig[rho_idx]
        # Beta ~ MN(Mu, V, Q): Mu + chol(V) @ N(0,1) @ sqrtQ'
        sqQ = data_par.U * np.sqrt(e)[None, :]
        Beta = Mu + np.linalg.cholesky(V) @ rng.standard_normal((nc, ns)) @ sqQ.T

    for r in range(spec.nr):
        rL = hM.ranLevels[r]
        ls = spec.levels[r]
        nf_max, ncr, np_r = ls.nf_max, ls.ncr, ls.n_units
        Delta = np.ones((nf_max, ncr))
        Delta[0] = rng.gamma(rL.a1, 1 / rL.b1)
        if nf_max > 1:
            Delta[1:] = rng.gamma(np.broadcast_to(rL.a2, (nf_max - 1, ncr)),
                                  1 / np.broadcast_to(rL.b2, (nf_max - 1, ncr)))
        Psi = rng.gamma(rL.nu / 2, 2 / rL.nu, (nf_max, ns, ncr))
        tau = np.cumprod(Delta, axis=0)
        Lambda = rng.standard_normal((nf_max, ns, ncr)) / np.sqrt(Psi * tau[:, None, :])
        if ls.spatial is None:
            alpha_idx = np.zeros(nf_max, dtype=np.int32)
            Eta = rng.standard_normal((np_r, nf_max))
        else:
            w = rL.alphapw[:, 1] / rL.alphapw[:, 1].sum()
            alpha_idx = rng.choice(len(w), size=nf_max, p=w).astype(np.int32)
            lp = data_par.rL_par[r]
            Eta = _spatial_prior_eta(hM, lp, r, alpha_idx, np_r, nf_max, rng)
        rec[f"Eta_{r}"] = Eta
        rec[f"Lambda_{r}"] = Lambda
        rec[f"Psi_{r}"] = Psi
        rec[f"Delta_{r}"] = Delta
        rec[f"Alpha_{r}"] = alpha_idx
        rec[f"nfMask_{r}"] = np.ones(nf_max)

    # selection: the recorded-prior Beta carries the same Bernoulli(q)
    # zero-mass per block that record_sample's masking induces
    for sel in hM.x_select:
        on = rng.uniform(size=len(sel.q)) < sel.q
        off_species = ~on[sel.sp_group]
        Beta[np.ix_(sel.cov_group, off_species)] = 0.0

    wRRR_raw = None
    if hM.nc_rrr > 0:
        DeltaRRR = np.concatenate([rng.gamma(hM.a1RRR, 1 / hM.b1RRR, 1),
                                   rng.gamma(hM.a2RRR, 1 / hM.b2RRR,
                                             hM.nc_rrr - 1)])
        PsiRRR = rng.gamma(hM.nuRRR / 2, 2 / hM.nuRRR,
                           (hM.nc_rrr, hM.nc_orrr))
        tau = np.cumprod(DeltaRRR)
        wRRR_raw = rng.standard_normal((hM.nc_rrr, hM.nc_orrr)) \
            / np.sqrt(PsiRRR * tau[:, None])
        rs = hM.xrrr_scale_par[1]
        rec.update(wRRR=wRRR_raw / rs[None, :], PsiRRR=PsiRRR,
                   DeltaRRR=DeltaRRR)

    # back-transform to original scale (combineParameters), numpy mirror
    Beta_t, Gamma_t, V_t = _combine_np(hM, Beta, Gamma, V)
    if wRRR_raw is not None and hM.x_intercept_ind is not None:
        # absorb the XRRR centering constant into the intercept, matching
        # record_sample's invariant (raw XRRR reproduces the scaled design)
        rm, rs = hM.xrrr_scale_par
        cK = (wRRR_raw * (rm / rs)[None, :]).sum(axis=1)     # (nc_rrr,)
        ncn = hM.nc_nrrr
        ii = hM.x_intercept_ind
        Beta_t[ii] -= (cK[:, None] * Beta_t[ncn:]).sum(axis=0)
        Gamma_t[ii] -= (cK[:, None] * Gamma_t[ncn:]).sum(axis=0)
    rec.update(Beta=Beta_t, Gamma=Gamma_t, V=V_t, sigma=sigma,
               rho=hM.rhopw[rho_idx, 0] if hM.C is not None else 0.0)
    return rec


def _combine_np(hM, Beta, Gamma, V):
    Beta, Gamma = Beta.copy(), Gamma.copy()
    iV = np.linalg.inv(V)
    tm, ts = hM.tr_scale_par
    Gamma = Gamma / ts[None, :]
    if hM.tr_intercept_ind is not None:
        ii = hM.tr_intercept_ind
        corr = (tm[None, :] * Gamma).sum(axis=1) - tm[ii] * Gamma[:, ii]
        Gamma[:, ii] -= corr
    xm, xs = hM.x_scale_par
    ncn = hM.nc_nrrr
    Beta[:ncn] = Beta[:ncn] / xs[:, None]
    Gamma[:ncn] = Gamma[:ncn] / xs[:, None]
    if hM.x_intercept_ind is not None:
        ii = hM.x_intercept_ind
        corrB = (xm[:, None] * Beta[:ncn]).sum(axis=0) - xm[ii] * Beta[ii]
        corrG = (xm[:, None] * Gamma[:ncn]).sum(axis=0) - xm[ii] * Gamma[ii]
        Beta[ii] -= corrB
        Gamma[ii] -= corrG
    iV[:ncn, :] = iV[:ncn, :] * xs[:, None]
    iV[:, :ncn] = iV[:, :ncn] * xs[None, :]
    return Beta, Gamma, np.linalg.inv(iV)


def sample_prior_chains(hM, spec, data_par, samples: int, n_chains: int, rng):
    recs = []
    for _ in range(n_chains):
        chain = [sample_prior(hM, spec, data_par, rng) for _ in range(samples)]
        recs.append(chain)
    # stack into (chains, samples, ...)
    keys = recs[0][0].keys()
    return {k: np.stack([[np.asarray(r[k]) for r in chain] for chain in recs])
            for k in keys}
