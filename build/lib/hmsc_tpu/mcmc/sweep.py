"""One Gibbs sweep in the reference's fixed update order
(``R/sampleMcmc.R:219-306``), assembled at trace time from static flags.

The sweep is a pure function ``(data, state, key) -> state`` suitable for
``lax.scan`` and ``vmap`` over chains.  Updaters can be disabled via the
``updater`` toggle dict exactly like the reference (``updater$Eta=FALSE`` ->
``updater={"Eta": False}``).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from . import updaters as U
from . import updaters_sel as USel
from .spatial import update_alpha, update_eta_spatial
from .structs import GibbsState, ModelData, ModelSpec

__all__ = ["make_sweep", "record_sample", "effective_spec_data"]


def effective_spec_data(spec: ModelSpec, data: ModelData, state: GibbsState):
    """(spec, data) with the state-dependent effective design in force —
    RRR columns appended, selection zeroing applied (no-op otherwise)."""
    if spec.nc_rrr == 0 and spec.ncsel == 0:
        return spec, data
    Xeff, per_species = USel.effective_design(spec, data, state)
    spec_x = (dataclasses.replace(spec, x_is_list=True)
              if per_species and not spec.x_is_list else spec)
    return spec_x, data.replace(X=Xeff)


def make_sweep(spec: ModelSpec, updater: dict | None = None,
               adapt_nf: tuple | None = None):
    updater = updater or {}
    on = lambda name: updater.get(name, True) is not False
    adapt_nf = adapt_nf or tuple(0 for _ in range(spec.nr))
    # RRR appends columns and selection zeroes per-species blocks: both make
    # the in-force design state-dependent, so downstream updaters see a
    # per-sweep effective X (and the per-species design path when selecting)
    has_dynamic_x = spec.nc_rrr > 0 or spec.ncsel > 0
    spec_x = (dataclasses.replace(spec, x_is_list=True)
              if spec.ncsel > 0 and not spec.x_is_list else spec)

    def with_eff_x(data, state):
        if not has_dynamic_x:
            return data
        Xeff, _ = USel.effective_design(spec, data, state)
        return data.replace(X=Xeff)

    # collapsed updaters are opt-in (see updaters_marginal module docstring);
    # the sampler validates their structural gates before enabling
    want = lambda name: updater.get(name, False) is True

    def sweep(data: ModelData, state: GibbsState, key) -> GibbsState:
        state = state.replace(it=state.it + 1)
        ks = jax.random.split(key, 13)
        data_x = with_eff_x(data, state)

        if want("Gamma2"):
            from .updaters_marginal import update_gamma2
            state = update_gamma2(spec_x, data_x, state, ks[10])
        if want("GammaEta"):
            from .updaters_marginal import update_gamma_eta
            for r in range(spec.nr):
                state = update_gamma_eta(spec_x, data_x, state, r,
                                         jax.random.fold_in(ks[11], r))
        if on("BetaLambda"):
            state = U.update_beta_lambda(spec_x, data_x, state, ks[0])
        if has_dynamic_x and spec.nr > 0:
            LRan_total = sum(U.level_loading(data.levels[r], state.levels[r])
                             for r in range(spec.nr))
        elif has_dynamic_x:
            LRan_total = jnp.zeros_like(state.Z)
        if spec.nc_rrr > 0 and on("wRRR"):
            state = USel.update_w_rrr(spec, data, state, ks[8], LRan_total)
            data_x = with_eff_x(data, state)
        if spec.ncsel > 0 and on("BetaSel"):
            state = USel.update_beta_sel(spec, data, state, ks[9], LRan_total)
            data_x = with_eff_x(data, state)
        if on("GammaV"):
            state = U.update_gamma_v(spec, data, state, ks[1])
        if spec.has_phylo and on("Rho"):
            state = U.update_rho(spec, data, state, ks[2])
        if on("LambdaPriors"):
            state = U.update_lambda_priors(spec, data, state, ks[3])
        if spec.nc_rrr > 0 and on("wRRRPriors"):
            state = USel.update_w_rrr_priors(spec, data, state,
                                             jax.random.fold_in(ks[8], 1))

        # E_shared: the current linear predictor, threaded through the sweep
        # tail (Eta -> InvSigma -> Z) so total_loading's padding-bound small-K
        # matmuls run once instead of three times per sweep
        E_shared = None
        if on("Eta") and spec.nr > 0:
            LFix = U.linear_fixed(spec_x, data_x, state.Beta)
            LRan = [U.level_loading(data.levels[r], state.levels[r])
                    for r in range(spec.nr)]
            for r in range(spec.nr):
                S = state.Z - LFix
                for q in range(spec.nr):
                    if q != r:
                        S = S - LRan[q]
                kr = jax.random.fold_in(ks[4], r)
                if spec.levels[r].spatial is None:
                    lv = U.update_eta_nonspatial(spec, data, state, r, kr, S)
                else:
                    lv = update_eta_spatial(spec, data, state, r, kr, S)
                levels = list(state.levels)
                levels[r] = lv
                state = state.replace(levels=tuple(levels))
                LRan[r] = U.level_loading(data.levels[r], state.levels[r])
            E_shared = LFix
            for r in range(spec.nr):
                E_shared = E_shared + LRan[r]

        if on("Alpha"):
            for r in range(spec.nr):
                if spec.levels[r].spatial is not None:
                    lv = update_alpha(spec, data, state, r,
                                      jax.random.fold_in(ks[5], r))
                    levels = list(state.levels)
                    levels[r] = lv
                    state = state.replace(levels=tuple(levels))

        # beyond-reference: per-factor (Eta, Lambda) scale interweaving
        # (measured 2x ESS on association scales) and the per-factor
        # (Eta, Beta_intercept) location move (measured +10% min / +20%
        # median Beta ESS at config 2 once the round-5 gate fix made it
        # actually run — benchmarks/ab_interweave_da.py).  Both default on,
        # both leave the linear predictor invariant, so E_shared stays
        # valid.  interweave_location self-gates (location_gate) on models
        # where its invariance breaks.  Gated on the updaters they perturb:
        # a frozen Eta/BetaLambda run (debugging, conditional sampling)
        # must not see drifting Eta/Lambda/Beta
        iw_ok = spec.nr > 0 and on("Eta") and on("BetaLambda")
        if iw_ok and (on("Interweave") or on("InterweaveLocation")):
            kI1, kI2 = jax.random.split(ks[12])
            if on("Interweave"):
                state = U.interweave_scale(spec, data, state, kI1)
            if on("InterweaveLocation"):
                state = U.interweave_location(spec, data, state, kI2)

        if on("InvSigma"):
            state = U.update_inv_sigma(spec_x, data_x, state, ks[6],
                                       E=E_shared)
        if on("Z"):
            state = U.update_z(spec_x, data_x, state, ks[7], E=E_shared)

        # opt-in ASIS flip of the probit augmentation on the intercept row
        # (updaters.interweave_da_intercept) — placed after updateZ so the
        # ancillary residual is built from the freshest Z; it changes Beta
        # and Z jointly, and nothing after it consumes E_shared
        if want("InterweaveDA") and on("Z") and on("BetaLambda"):
            state = U.interweave_da_intercept(
                spec, data, state, jax.random.fold_in(ks[7], 1))

        # factor-count adaptation during burn-in (iter <= adaptNf[r])
        for r in range(spec.nr):
            if adapt_nf[r] > 0 and on("Nf"):
                kr = jax.random.fold_in(ks[5], 1000 + r)
                lv_new = U.update_nf(spec, data, state, r, kr)
                gate = (state.it <= adapt_nf[r])
                lv_old = state.levels[r]
                lv = jax.tree.map(
                    lambda a, b: jnp.where(gate, a, b), lv_new, lv_old)
                levels = list(state.levels)
                levels[r] = lv
                state = state.replace(levels=tuple(levels))
        return state

    return sweep


# ---------------------------------------------------------------------------
# combineParameters at record time (reference R/combineParameters.R:1-58)
# ---------------------------------------------------------------------------

def record_sample(spec: ModelSpec, data: ModelData, state: GibbsState) -> dict:
    """Back-transform the current state to the original X/Tr scale and return
    the posterior-sample pytree (the postList schema, SURVEY.md §2.2)."""
    Beta = state.Beta
    Gamma = state.Gamma
    iV = state.iV

    # selection: zero the switched-off covariate blocks FIRST, so the
    # centering/intercept corrections below operate on the effective Beta
    # (the reference zeroes after back-transform, combineParameters.R:45-53,
    # which mis-absorbs off-block slab coefficients into the intercept when
    # X is centered)
    if spec.ncsel > 0:
        from .updaters_sel import selection_mask
        Beta = Beta * selection_mask(spec, data, state.BetaSel).T

    # traits: Gamma columns back to raw-trait scale
    tm, ts = data.tr_scale_par[0], data.tr_scale_par[1]
    Gamma = Gamma / ts[None, :]
    if data.tr_intercept_ind is not None:
        corr = (tm[None, :] * Gamma).sum(axis=1) - tm[data.tr_intercept_ind] * Gamma[:, data.tr_intercept_ind]
        Gamma = Gamma.at[:, data.tr_intercept_ind].add(-corr)

    # covariates: Beta/Gamma rows and iV rows+cols
    xm = data.x_scale_par[0], data.x_scale_par[1]
    xmean, xs = xm
    ncn = spec.nc_nrrr
    scale_rows = jnp.concatenate(
        [xs, jnp.ones(spec.nc - ncn, dtype=xs.dtype)]) if spec.nc > ncn else xs
    mean_rows = jnp.concatenate(
        [xmean, jnp.zeros(spec.nc - ncn, dtype=xmean.dtype)]) if spec.nc > ncn else xmean
    Beta = Beta / scale_rows[:, None]
    Gamma = Gamma / scale_rows[:, None]
    if data.x_intercept_ind is not None:
        ii = data.x_intercept_ind
        corrB = (mean_rows[:, None] * Beta).sum(axis=0) - mean_rows[ii] * Beta[ii]
        corrG = (mean_rows[:, None] * Gamma).sum(axis=0) - mean_rows[ii] * Gamma[ii]
        Beta = Beta.at[ii].add(-corrB)
        Gamma = Gamma.at[ii].add(-corrG)
    iV_t = iV * scale_rows[:, None] * scale_rows[None, :]
    V = jnp.linalg.inv(iV_t)

    # RRR: back-transform wRRR so raw XRRR reproduces the scaled design
    # (XB_raw @ wRRR_rec' == XRRRScaled @ wRRR'), with the centering constant
    # absorbed into the intercept row of Beta/Gamma.  The reference instead
    # divides Beta's RRR rows by XRRRScalePar[,k] (combineParameters.R:30-43),
    # which mixes per-original-covariate scales into per-component rows; the
    # invariant above is the one predict()/WAIC rely on.
    wRRR = state.wRRR
    if spec.nc_rrr > 0 and data.xrrr_scale_par is not None:
        rm, rs = data.xrrr_scale_par[0], data.xrrr_scale_par[1]
        wRRR = state.wRRR / rs[None, :]
        if data.x_intercept_ind is not None:
            ii = data.x_intercept_ind
            cK = (state.wRRR * (rm / rs)[None, :]).sum(axis=1)  # (nc_rrr,)
            Beta = Beta.at[ii].add(-(cK[:, None] * Beta[ncn:]).sum(axis=0))
            Gamma = Gamma.at[ii].add(-(cK[:, None] * Gamma[ncn:]).sum(axis=0))

    rec = {
        "Beta": Beta,
        "Gamma": Gamma,
        "V": V,
        "sigma": 1.0 / state.iSigma,
        "rho": (data.rhopw[state.rho_idx, 0] if spec.has_phylo
                else jnp.zeros((), dtype=Beta.dtype)),
    }
    for r in range(spec.nr):
        lv = state.levels[r]
        rec[f"Eta_{r}"] = lv.Eta
        rec[f"Lambda_{r}"] = U.lambda_effective(lv)
        rec[f"Psi_{r}"] = lv.Psi
        rec[f"Delta_{r}"] = lv.Delta
        rec[f"Alpha_{r}"] = lv.alpha_idx
        rec[f"nfMask_{r}"] = lv.nf_mask
    if spec.nc_rrr > 0:
        rec["wRRR"] = wRRR
        rec["PsiRRR"] = state.PsiRRR
        rec["DeltaRRR"] = state.DeltaRRR
    return rec
