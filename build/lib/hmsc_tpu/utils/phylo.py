"""Phylogenetic-tree ingestion: Newick parsing and the Brownian-motion
correlation matrix (the reference accepts ``phyloTree`` and converts it via
``ape::vcv.phylo(model="Brownian", corr=TRUE)``, ``R/Hmsc.R:504-509``; here
the tree arrives as a Newick string — the lingua franca outside R)."""

from __future__ import annotations

import numpy as np

__all__ = ["parse_newick", "phylo_corr", "prune_parsed", "vcv_from_newick"]


def _clean(newick: str) -> str:
    """Strip [...] comments and whitespace outside quoted labels.

    Inside a quoted label the Newick escape ``''`` (doubled apostrophe)
    stands for a literal apostrophe and does not terminate the quote.
    """
    out, depth, quoted = [], 0, False
    i, n = 0, len(newick)
    while i < n:
        ch = newick[i]
        if quoted:
            out.append(ch)
            if ch == "'":
                if i + 1 < n and newick[i + 1] == "'":
                    out.append("'")       # escaped quote: keep both, stay quoted
                    i += 1
                else:
                    quoted = False
        elif ch == "[":
            depth += 1
        elif ch == "]":
            depth = max(0, depth - 1)
        elif depth == 0:
            if ch == "'":
                quoted = True
                out.append(ch)
            elif not ch.isspace():
                out.append(ch)
        i += 1
    return "".join(out)


def parse_newick(newick: str):
    """Parse a Newick string into ``(children, lengths, names)``.

    - ``children``: list per node of child node ids (empty for leaves);
    - ``lengths``: branch length from each node to its parent (root: 0.0);
      every non-root edge must carry an explicit ``:length`` — like the
      ``ape::vcv.phylo`` path this mirrors, a topology-only tree is an
      error, not a fabricated unit-length tree;
    - ``names``: node labels ('' for unnamed internals).

    Node ids are topologically ordered (every parent precedes its
    children); node 0 is the root.  Quoted labels ('...'), whitespace and
    ``[...]`` comments are handled.  The parser and the vcv accumulation
    are iterative, so deep (pectinate) trees of any size parse without
    hitting the recursion limit.
    """
    s = _clean(newick)
    if s.endswith(";"):
        s = s[:-1]
    if not s:
        raise ValueError("Hmsc.parse_newick: empty tree string")

    children: list[list[int]] = []
    lengths: list[float | None] = []
    names: list[str] = []

    def new_node(parent):
        children.append([])
        lengths.append(None)
        names.append("")
        node = len(children) - 1
        if parent is not None:
            children[parent].append(node)
        return node

    def read_label(i, node):
        """Optional name[:length] attached to ``node``; returns new i."""
        if i < len(s) and s[i] == "'":
            # '' inside the label is the Newick escape for a literal quote
            j, buf = i + 1, []
            while j < len(s):
                if s[j] == "'":
                    if j + 1 < len(s) and s[j + 1] == "'":
                        buf.append("'")
                        j += 2
                        continue
                    break
                buf.append(s[j])
                j += 1
            if j >= len(s):
                raise ValueError("Hmsc.parse_newick: unterminated quoted label")
            names[node] = "".join(buf)
            i = j + 1
        else:
            j = i
            while j < len(s) and s[j] not in ",():;":
                j += 1
            names[node] = s[i:j]
            i = j
        if i < len(s) and s[i] == ":":
            j = i + 1
            while j < len(s) and s[j] not in ",()":
                j += 1
            try:
                lengths[node] = float(s[i + 1:j])
            except ValueError:
                raise ValueError(
                    f"Hmsc.parse_newick: bad branch length {s[i + 1:j]!r}")
            i = j
        return i

    stack: list[int] = []
    root = None
    i = 0
    expect_node = True            # position where a subtree/leaf may start
    while i < len(s):
        ch = s[i]
        if ch == "(":
            if not expect_node:
                raise ValueError(
                    f"Hmsc.parse_newick: unexpected '(' at {i}")
            node = new_node(stack[-1] if stack else None)
            if root is None:
                root = node
            stack.append(node)
            i += 1
        elif ch == ",":
            if not stack:
                raise ValueError(
                    f"Hmsc.parse_newick: ',' outside any group at {i}")
            expect_node = True
            i += 1
        elif ch == ")":
            if not stack:
                raise ValueError("Hmsc.parse_newick: unbalanced ')'")
            node = stack.pop()
            i = read_label(i + 1, node)
            expect_node = False
        else:                     # a leaf (or the bare single-node tree)
            if not expect_node:
                raise ValueError(
                    f"Hmsc.parse_newick: unexpected {ch!r} at {i}")
            node = new_node(stack[-1] if stack else None)
            if root is None:
                root = node
            i = read_label(i, node)
            expect_node = False
    if stack:
        raise ValueError("Hmsc.parse_newick: unbalanced tree string")

    lengths[root] = 0.0
    missing = [names[v] or f"node {v}" for v in range(len(lengths))
               if lengths[v] is None]
    if missing:
        raise ValueError(
            "Hmsc.parse_newick: the tree must have branch lengths on every "
            f"edge (missing on {missing[:5]}{'...' if len(missing) > 5 else ''})")
    # parents were created before their children, so ids are topological;
    # root relabelled to 0 for the documented contract
    if root != 0:  # pragma: no cover — current construction always has root 0
        order = [root] + [v for v in range(len(children)) if v != root]
        inv = {v: k for k, v in enumerate(order)}
        children = [[inv[c] for c in children[v]] for v in order]
        lengths = [lengths[v] for v in order]
        names = [names[v] for v in order]
    return children, lengths, names


def prune_parsed(children, lengths, names, keep_leaves):
    """Restrict a parsed tree to the leaves in ``keep_leaves`` (the
    ``ape::keep.tip`` operation plotBeta needs when the supplied tree covers
    more species than the model): dropped subtrees are removed and unary
    chains are collapsed with branch lengths summed.  Returns a new
    ``(children, lengths, names)`` triple with the same id contract as
    :func:`parse_newick` (parents precede children, root is 0)."""
    keep = set(map(str, keep_leaves))
    n = len(children)
    sub = [None] * n
    for v in range(n - 1, -1, -1):           # children before parents
        if not children[v]:
            if names[v] in keep:
                sub[v] = {"len": lengths[v], "ch": [], "name": names[v]}
        else:
            ch = [sub[c] for c in children[v] if sub[c] is not None]
            if not ch:
                continue
            if len(ch) == 1:                 # collapse the unary chain
                c = ch[0]
                sub[v] = {"len": lengths[v] + c["len"], "ch": c["ch"],
                          "name": c["name"]}
            else:
                sub[v] = {"len": lengths[v], "ch": ch, "name": names[v]}
    root = sub[0]
    if root is None:
        raise ValueError(
            "Hmsc.prune_parsed: no requested leaf is present in the tree")
    root = dict(root, len=0.0)               # root carries no branch
    out_ch, out_len, out_nm = [], [], []
    stack = [(root, None)]
    while stack:                             # parent-before-child ids
        node, parent = stack.pop()
        out_ch.append([])
        out_len.append(node["len"])
        out_nm.append(node["name"])
        vid = len(out_ch) - 1
        if parent is not None:
            out_ch[parent].append(vid)
        for c in reversed(node["ch"]):
            stack.append((c, vid))
    return out_ch, out_len, out_nm


def vcv_from_newick(newick: str):
    """Brownian-motion phylogenetic covariance over the leaves:
    ``cov[i, j]`` = summed branch length shared by the root-to-leaf paths
    (``ape::vcv.phylo(model="Brownian")``).  Returns ``(V, leaf_names)``."""
    children, lengths, names = parse_newick(newick)
    n_nodes = len(children)
    leaves = [v for v in range(n_nodes) if not children[v]]
    if any(not names[v] for v in leaves):
        raise ValueError("Hmsc.vcv_from_newick: every leaf must be named")
    leaf_names = [names[v] for v in leaves]
    if len(set(leaf_names)) != len(leaf_names):
        dup = sorted({n for n in leaf_names if leaf_names.count(n) > 1})
        raise ValueError(
            f"Hmsc.vcv_from_newick: duplicated leaf names {dup[:5]} — tip "
            "labels must be unique (ape::vcv.phylo errors here too)")
    leaf_ix = {v: k for k, v in enumerate(leaves)}
    n = len(leaves)
    V = np.zeros((n, n))
    # bottom-up leaf sets without recursion: ids are parent-before-child
    leafset: list[list[int] | None] = [None] * n_nodes
    for v in range(n_nodes - 1, -1, -1):
        if not children[v]:
            leafset[v] = [leaf_ix[v]]
        else:
            acc = []
            for c in children[v]:
                acc.extend(leafset[c])
                leafset[c] = None          # free as we go
            leafset[v] = acc
        ia = np.asarray(leafset[v])
        V[np.ix_(ia, ia)] += lengths[v]
    return V, [names[v] for v in leaves]


def phylo_corr(newick: str, sp_names=None):
    """Brownian correlation matrix over species, ordered like ``sp_names``
    (the reference's ``corM[spNames, spNames]`` reindex, ``Hmsc.R:505-506``).
    With ``sp_names=None`` the tree's own leaf order is kept."""
    V, leaves = vcv_from_newick(newick)
    d = np.sqrt(np.diag(V))
    if np.any(d <= 0):
        raise ValueError(
            "Hmsc.phylo_corr: zero root-to-leaf distance; the tree needs "
            "positive branch lengths")
    C = V / d[:, None] / d[None, :]
    if sp_names is None:
        return C, leaves
    pos = {name: k for k, name in enumerate(leaves)}
    missing = [s for s in map(str, sp_names) if s not in pos]
    if missing:
        raise ValueError(
            f"Hmsc.setData: phylogenetic tree is missing species {missing}")
    ix = np.asarray([pos[str(s)] for s in sp_names])
    return C[np.ix_(ix, ix)], [leaves[k] for k in ix]
