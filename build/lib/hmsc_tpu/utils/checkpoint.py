"""Checkpoint / resume for long MCMC runs (SURVEY.md §5: the reference has no
in-process checkpointing — its idiom is R serialization of the fitted object
plus ``initPar`` warm starts; here (samples-so-far, carry-state) snapshots
are first-class).

Layout: one ``.npz`` holding the recorded posterior arrays (``post:<name>``),
the chain carry-state pytree leaves (``state:<i>``) with a pickled treedef,
and the run metadata.  ``load_checkpoint`` + ``sample_mcmc(init_state=...)``
continues the chains bit-exactly where they left off (modulo the fresh RNG
stream seeded for the continuation), and ``Posterior.concat`` splices the
segments.
"""

from __future__ import annotations

import pickle

import numpy as np

__all__ = ["save_checkpoint", "load_checkpoint", "concat_posteriors"]


def save_checkpoint(path: str, post, state) -> None:
    """Write a resumable snapshot: the Posterior so far + the carry state
    from ``sample_mcmc(..., return_state=True)``."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(state)
    payload = {f"post:{k}": v for k, v in post.arrays.items()}
    payload.update({f"state:{i}": np.asarray(x) for i, x in enumerate(leaves)})
    payload["meta"] = np.frombuffer(pickle.dumps({
        "samples": post.samples, "transient": post.transient,
        "thin": post.thin, "treedef": treedef}), dtype=np.uint8)
    with open(path, "wb") as f:
        np.savez_compressed(f, **payload)


def load_checkpoint(path: str, hM):
    """Returns (Posterior, carry_state) ready for
    ``sample_mcmc(hM, ..., init_state=carry_state)``."""
    import jax.numpy as jnp
    from jax.tree_util import tree_unflatten

    from ..mcmc.structs import build_spec
    from ..post.posterior import Posterior

    with np.load(path, allow_pickle=False) as z:
        meta = pickle.loads(z["meta"].tobytes())
        arrays = {k[5:]: z[k] for k in z.files if k.startswith("post:")}
        n_state = sum(1 for k in z.files if k.startswith("state:"))
        leaves = [jnp.asarray(z[f"state:{i}"]) for i in range(n_state)]
    state = tree_unflatten(meta["treedef"], leaves)
    spec = build_spec(hM)
    post = Posterior(hM, spec, arrays, samples=meta["samples"],
                     transient=meta["transient"], thin=meta["thin"])
    return post, state


def concat_posteriors(first, second):
    """Splice two sampling segments of the same model (chains must match):
    the recorded-sample axis is concatenated per parameter."""
    if first.n_chains != second.n_chains:
        raise ValueError("concat_posteriors: chain counts differ")
    arrays = {k: np.concatenate([first.arrays[k], second.arrays[k]], axis=1)
              for k in first.arrays}
    from ..post.posterior import Posterior

    out = Posterior(first.hM, first.spec, arrays,
                    samples=first.samples + second.samples,
                    transient=first.transient, thin=first.thin)
    # segments may have been sign-aligned against their own posterior-mean
    # Lambda; re-align per (chain, sample) over the spliced window so factor
    # signs are consistent across segments
    if first.spec.nr > 0:
        from ..post.align import align_posterior
        for _ in range(5):
            align_posterior(out)
    return out
