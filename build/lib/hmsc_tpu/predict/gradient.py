"""Environmental-gradient construction for prediction (reference
``R/constructGradient.R:39-216``, ``R/prepareGradient.R:31-66``).

``construct_gradient`` builds a prediction design where a focal variable
sweeps a grid and every non-focal variable is set by one of three policies
(matching the reference's ``non.focalVariables`` codes):

1. most-likely value (mode for factors, mean for numeric),
2. value predicted from a regression on the focal variable (default; linear
   regression for numeric, multinomial logistic for factors),
3. a fixed user-given value.

A single ``new_unit`` is appended to every random level (centroid coordinates
for coordinate-based levels, a near-medoid pseudo-distance row for
distance-matrix levels) — reference ``constructGradient.R:180-212``.
The reference's ``sprintf('new_unit', 1:ngrid)`` yields the *same* unit name
for every gradient point (one shared new unit); that behavior is kept
deliberately.
"""

from __future__ import annotations

import re

import numpy as np
import pandas as pd

__all__ = ["construct_gradient", "prepare_gradient"]


def _formula_vars(formula: str, df) -> list[str]:
    """Data-frame columns referenced by the formula (R's ``all.vars``).
    A ``.`` term anywhere in the RHS pulls in every column."""
    rhs = formula.split("~", 1)[-1]
    toks = set(re.findall(r"[A-Za-z_.][\w.]*", rhs))
    has_dot = bool(re.search(r"(^|[^\w.])\.($|[^\w.])", rhs.strip()))
    return [str(c) for c in df.columns if str(c) in toks or has_dot]


def _mode(values) -> object:
    vals, counts = np.unique(np.asarray(values), return_counts=True)
    return vals[np.argmax(counts)]


def _multinom_predict(y_labels, x, x_new):
    """Most-probable class from a small multinomial-logistic fit of a factor
    on the focal variable (reference uses ``nnet::multinom``,
    ``constructGradient.R:146-148``)."""
    classes = sorted(set(map(str, y_labels)))
    K = len(classes)
    if K == 1:
        return np.array([classes[0]] * len(x_new))
    Yi = np.array([classes.index(str(v)) for v in y_labels])
    X = np.column_stack([np.ones(len(x)), np.asarray(x, dtype=float)])
    W = np.zeros((2, K))
    Y1h = np.eye(K)[Yi]
    for _ in range(200):                      # tiny IRLS-flavoured GD
        P = np.exp(X @ W - (X @ W).max(axis=1, keepdims=True))
        P /= P.sum(axis=1, keepdims=True)
        g = X.T @ (P - Y1h) / len(x)
        W -= 1.0 * g
    Xn = np.column_stack([np.ones(len(x_new)), np.asarray(x_new, dtype=float)])
    return np.array([classes[i] for i in (Xn @ W).argmax(axis=1)])


def construct_gradient(hM, focal_variable: str, non_focal_variables=None,
                       ngrid: int = 20) -> dict:
    """Returns ``{"XDataNew", "studyDesignNew", "rLNew"}`` for ``predict``."""
    from ..random_level import HmscRandomLevel, set_priors_random_level

    non_focal_variables = dict(non_focal_variables or {})
    if hM.x_data is None or isinstance(hM.x_data, (list, tuple)):
        xdf = hM.x_data[0] if isinstance(hM.x_data, (list, tuple)) else None
        if xdf is None:
            raise ValueError("constructGradient requires the model to be built from XData + XFormula")
    else:
        xdf = hM.x_data
    vars_ = _formula_vars(hM.x_formula, xdf)
    if focal_variable not in vars_:
        raise ValueError(f"constructGradient: focal variable {focal_variable!r} not among formula variables {vars_}")

    v_focal = xdf[focal_variable]
    is_factor = np.asarray(v_focal).dtype.kind in "OUSb"
    if is_factor:
        xx = sorted(set(map(str, np.asarray(v_focal))))
        ngrid = len(xx)
    else:
        v = np.asarray(v_focal, dtype=float)
        xx = np.linspace(v.min(), v.max(), ngrid)
    x_new = pd.DataFrame({focal_variable: xx})

    for var in vars_:
        if var == focal_variable:
            continue
        spec = non_focal_variables.get(var)
        type_ = int(spec[0]) if spec is not None else 2
        val = spec[1] if (spec is not None and len(spec) > 1) else None
        col = xdf[var]
        f_nf = np.asarray(col).dtype.kind in "OUSb"
        if type_ == 1:
            x_new[var] = (_mode(col) if f_nf
                          else float(np.mean(np.asarray(col, dtype=float))))
        elif type_ == 3:
            x_new[var] = [val] * ngrid
        else:  # type 2: regression on the focal variable
            if is_factor:
                # focal is a factor: use group means / modes per level
                grp = pd.Series(np.asarray(col), index=None).groupby(
                    np.asarray(v_focal).astype(str))
                if f_nf:
                    x_new[var] = [_mode(grp.get_group(g)) for g in xx]
                else:
                    x_new[var] = [float(np.mean(np.asarray(
                        grp.get_group(g), dtype=float))) for g in xx]
            elif f_nf:
                x_new[var] = _multinom_predict(np.asarray(col),
                                               np.asarray(v_focal, float), xx)
            else:
                b = np.polyfit(np.asarray(v_focal, float),
                               np.asarray(col, float), 1)
                x_new[var] = np.polyval(b, xx)

    study_new = pd.DataFrame({name: ["new_unit"] * ngrid
                              for name in hM.rl_names})
    rl_new = {}
    for r, name in enumerate(hM.rl_names):
        rL = hM.ranLevels[r]
        if rL.s is not None:
            units1 = list(rL._s_index.keys()) + ["new_unit"]
            s1 = np.vstack([rL.s, rL.s.mean(axis=0)])
            rL1 = HmscRandomLevel(
                s_data=pd.DataFrame(s1, index=units1),
                s_method=rL.spatial_method,
                n_neighbours=rL.n_neighbours,
                s_knot=rL.s_knot)
        elif rL.dist_mat is not None:
            rm = rL.dist_mat.mean(axis=1)
            focals = np.argsort(rm)[:2]
            newdist = rL.dist_mat[focals].mean(axis=0)
            dm1 = np.vstack([np.column_stack([rL.dist_mat, newdist]),
                             np.append(newdist, 0.0)[None, :]])
            units1 = list(rL._dist_names) + ["new_unit"]
            rL1 = HmscRandomLevel(dist_mat=pd.DataFrame(dm1, index=units1),
                                  s_method=rL.spatial_method)
        elif rL.x_dim > 0:
            # covariate-dependent level: the new unit gets the mean covariates
            units1 = list(rL._x_index.keys()) + ["new_unit"]
            x1 = np.vstack([rL.x, rL.x.mean(axis=0)])
            rL1 = HmscRandomLevel(x_data=pd.DataFrame(x1, index=units1))
        else:
            rL1 = HmscRandomLevel(units=list(rL.pi) + ["new_unit"])
        set_priors_random_level(rL1, nu=rL.nu, a1=rL.a1, b1=rL.b1, a2=rL.a2,
                                b2=rL.b2, alphapw=rL.alphapw,
                                nf_max=rL.nf_max, nf_min=rL.nf_min)
        rl_new[name] = rL1
    return {"XDataNew": x_new, "studyDesignNew": study_new, "rLNew": rl_new}


def prepare_gradient(hM, x_data_new, s_data_new=None) -> dict:
    """Wrap user-supplied new covariates (+ spatial coordinates per level)
    into the Gradient structure (reference ``prepareGradient.R:31-66``)."""
    from ..random_level import HmscRandomLevel, set_priors_random_level

    ny_new = len(x_data_new)
    study = {}
    rl_new = {}
    s_data_new = dict(s_data_new or {})
    for r, name in enumerate(hM.rl_names):
        rL = hM.ranLevels[r]
        if rL.s_dim == 0:
            study[name] = ["new_unit"] * ny_new
            if rL.x_dim > 0:
                units1 = list(rL._x_index.keys()) + ["new_unit"]
                x1 = np.vstack([rL.x, rL.x.mean(axis=0)])
                rL1 = HmscRandomLevel(x_data=pd.DataFrame(x1, index=units1))
            else:
                rL1 = HmscRandomLevel(units=list(rL.pi) + ["new_unit"])
        else:
            if name not in s_data_new:
                raise ValueError(f"prepareGradient: sDataNew must contain coordinates for spatial level {name!r}")
            xy_new = np.asarray(s_data_new[name], dtype=float)
            labels = [f"new_spatial_unit{i+1:06d}" for i in range(len(xy_new))]
            study[name] = labels
            units1 = list(rL._s_index.keys()) + labels
            s1 = np.vstack([rL.s, xy_new])
            rL1 = HmscRandomLevel(s_data=pd.DataFrame(s1, index=units1),
                                  s_method=rL.spatial_method,
                                  n_neighbours=rL.n_neighbours,
                                  s_knot=rL.s_knot)
        set_priors_random_level(rL1, nu=rL.nu, a1=rL.a1, b1=rL.b1, a2=rL.a2,
                                b2=rL.b2, alphapw=rL.alphapw,
                                nf_max=rL.nf_max, nf_min=rL.nf_min)
        rl_new[name] = rL1
    return {"XDataNew": x_data_new,
            "studyDesignNew": pd.DataFrame(study) if study else None,
            "rLNew": rl_new}
