"""Prediction & cross-validation layer (L5): posterior-predictive draws at
new covariates/units, latent-factor kriging (Full/NNGP/GPP), conditional
prediction, k-fold CV with per-fold refits, and gradient construction.

Reference surface: ``R/predict.R``, ``R/predictLatentFactor.R``,
``R/computePredictedValues.R``, ``R/createPartition.R``,
``R/constructGradient.R``, ``R/prepareGradient.R``.
"""

from .latent import predict_latent_factor
from .predict import predict
from .cv import compute_predicted_values, create_partition
from .gradient import construct_gradient, prepare_gradient

__all__ = ["predict", "predict_latent_factor", "compute_predicted_values",
           "create_partition", "construct_gradient", "prepare_gradient"]
