"""Random-level specification (reference ``R/HmscRandomLevel.R:38-94``,
``R/setPriors.HmscRandomLevel.R:18-110``).

A random level describes one grouping factor of the study design whose units
carry latent factors: unstructured, spatially structured (``Full`` exact GP,
``GPP`` knot-based predictive process, ``NNGP`` nearest-neighbour GP), built
from a distance matrix, or covariate-dependent (``x_data``).
"""

from __future__ import annotations

import numpy as np

__all__ = ["HmscRandomLevel", "set_priors_random_level"]

_SPATIAL_METHODS = ("Full", "GPP", "NNGP")


class HmscRandomLevel:
    """Specification of one random level.

    Exactly one of ``s_data`` (spatial coordinates), ``dist_mat``, ``units``,
    or ``n_units`` identifies the level's units; ``x_data`` adds
    covariate-dependent associations and may be combined with the others
    (mirroring the reference's argument contract).
    """

    def __init__(self, s_data=None, s_method: str = "Full", dist_mat=None,
                 x_data=None, units=None, n_units=None, n_neighbours=None,
                 s_knot=None, priors: bool = True):
        if all(a is None for a in (s_data, dist_mat, x_data, units, n_units)):
            raise ValueError("HmscRandomLevel: At least one argument must be specified")
        if s_data is not None and dist_mat is not None:
            raise ValueError("HmscRandomLevel: sData and distMat cannot both be specified")
        if s_method not in _SPATIAL_METHODS:
            raise ValueError(f"HmscRandomLevel: sMethod must be one of {_SPATIAL_METHODS}")

        self.pi: list[str] | None = None   # unit names
        self.s = None                      # (N, sDim) coordinates
        self.s_dim = 0
        self.spatial_method = None
        self.x = None                      # (N, xDim) covariate values
        self.x_dim = 0
        self.N: int | None = None
        self.dist_mat = None
        self.n_neighbours = n_neighbours
        self.s_knot = None

        if s_data is not None:
            s_arr, s_names = _as_named_matrix(s_data, "sData")
            self.s = s_arr
            self.N = s_arr.shape[0]
            self.pi = sorted(s_names)
            # keep coordinate rows addressable by unit name
            self._s_index = {n: i for i, n in enumerate(s_names)}
            self.s_dim = s_arr.shape[1]
            self.spatial_method = s_method
            self.s_knot = None if s_knot is None else np.asarray(s_knot, dtype=float)
        if dist_mat is not None:
            dm, dm_names = _as_named_matrix(dist_mat, "distMat")
            if dm.shape[0] != dm.shape[1]:
                raise ValueError("HmscRandomLevel: distMat must be a square matrix")
            self.dist_mat = dm
            self._dist_names = dm_names
            self.N = dm.shape[0]
            self.pi = sorted(dm_names)
            self.spatial_method = s_method
            self.s_dim = np.inf
        if x_data is not None:
            x_arr, x_names = _as_named_matrix(x_data, "xData")
            if self.pi is not None:
                if any(n not in self.pi for n in x_names):
                    raise ValueError("HmscRandomLevel: duplicated specification of unit names")
            else:
                self.pi = sorted(x_names)
                self.N = x_arr.shape[0]
            self.x_dim = x_arr.shape[1]
            self.x = x_arr
            self._x_index = {n: i for i, n in enumerate(x_names)}
        if units is not None:
            if self.pi is not None:
                raise ValueError("HmscRandomLevel: duplicated specification of unit names")
            self.pi = [str(u) for u in dict.fromkeys(units)]
            self.N = len(self.pi)
            self.s_dim = 0
        if n_units is not None:
            if self.pi is not None:
                raise ValueError("HmscRandomLevel: duplicated specification of the number of units")
            self.N = int(n_units)
            self.pi = [str(i + 1) for i in range(self.N)]
            self.s_dim = 0

        # shrinkage-prior fields filled by set_priors_random_level
        self.nu = self.a1 = self.b1 = self.a2 = self.b2 = None
        self.alphapw = None
        self.nf_max: float = np.inf
        self.nf_min: int = 2
        if priors:
            set_priors_random_level(self, set_default=True)

    # -- conveniences -------------------------------------------------------
    def coords_for(self, unit_names) -> np.ndarray:
        """Coordinate rows for the given unit names (reference indexes ``s``
        by ``levels(dfPi)``, ``computeDataParameters.R:62``)."""
        return self.s[[self._s_index[str(n)] for n in unit_names], :]

    def dist_for(self, unit_names) -> np.ndarray:
        idx = [self._dist_names.index(str(n)) for n in unit_names]
        return self.dist_mat[np.ix_(idx, idx)]

    def x_for(self, unit_names) -> np.ndarray:
        return self.x[[self._x_index[str(n)] for n in unit_names], :]

    def __repr__(self):
        kind = ("spatial" if self.s_dim not in (0,) else
                ("covariate-dependent" if self.x_dim > 0 else "unstructured"))
        return (f"HmscRandomLevel({kind}, N={self.N}"
                + (f", method={self.spatial_method}" if self.spatial_method else "")
                + ")")


def _as_named_matrix(data, what: str) -> tuple[np.ndarray, list[str]]:
    """Accept a pandas DataFrame (row-name aware) or ndarray."""
    if hasattr(data, "values") and hasattr(data, "index"):
        return np.asarray(data.values, dtype=float), [str(i) for i in data.index]
    arr = np.asarray(data, dtype=float)
    if arr.ndim == 1:
        arr = arr[:, None]
    return arr, [str(i + 1) for i in range(arr.shape[0])]


def set_priors_random_level(rL: HmscRandomLevel, nu=None, a1=None, b1=None,
                            a2=None, b2=None, alphapw=None, nf_max=None,
                            nf_min=None, set_default: bool = False) -> HmscRandomLevel:
    """Multiplicative-gamma shrinkage prior (Bhattacharya-Dunson) and the
    discrete spatial-range grid (reference ``setPriors.HmscRandomLevel.R``)."""
    x_dim = max(rL.x_dim, 1)

    def _vec(val, default, name):
        if val is None:
            return np.full(x_dim, float(default)) if set_default else getattr(rL, name)
        val = np.atleast_1d(np.asarray(val, dtype=float))
        if val.size == 1:
            return np.full(x_dim, float(val[0]))
        if val.size != x_dim:
            raise ValueError(
                f"HmscRandomLevel.setPriors: length of {name} argument must be either 1 or rL$xDim")
        return val

    rL.nu = _vec(nu, 3, "nu")
    rL.a1 = _vec(a1, 50, "a1")
    rL.b1 = _vec(b1, 1, "b1")
    rL.a2 = _vec(a2, 50, "a2")
    rL.b2 = _vec(b2, 1, "b2")

    if alphapw is not None:
        if rL.s_dim == 0:
            raise ValueError("HmscRandomLevel.setPriors: prior for spatial scale was given, "
                             "but not spatial coordinates were specified")
        alphapw = np.asarray(alphapw, dtype=float)
        if alphapw.ndim != 2 or alphapw.shape[1] != 2:
            raise ValueError("HmscRandomLevel.setPriors: alphapw must be a matrix with two columns")
        rL.alphapw = alphapw
    elif set_default and rL.s_dim != 0:
        # 101-point grid: 0 .. bounding-box diagonal (or max distance),
        # P(alpha=0)=0.5, the rest uniform
        alpha_n = 100
        if rL.dist_mat is None:
            diag = float(np.sqrt(np.sum((rL.s.max(axis=0) - rL.s.min(axis=0)) ** 2)))
        else:
            diag = float(rL.dist_mat.max())
        grid = diag * np.arange(alpha_n + 1) / alpha_n
        w = np.concatenate([[0.5], np.full(alpha_n, 0.5 / alpha_n)])
        rL.alphapw = np.column_stack([grid, w])

    if nf_max is not None:
        rL.nf_max = nf_max
    elif set_default:
        rL.nf_max = np.inf
    if nf_min is not None:
        if nf_min > rL.nf_max:
            raise ValueError("HmscRandomLevel.setPriors: nfMin must be not greater than nfMax")
        rL.nf_min = int(nf_min)
    elif set_default:
        rL.nf_min = 2
    return rL
