"""Convergence diagnostics: effective sample size and split-R-hat, plus the
coda-style named export (reference delegates to the ``coda`` package via
``R/convertToCodaObject.r``; we compute ESS/PSRF in-house with the standard
Geyer initial-monotone-sequence and Gelman-Rubin split-chain estimators)."""

from __future__ import annotations

import numpy as np

__all__ = ["effective_size", "gelman_rhat", "convert_to_coda_object",
           "CodaExport"]


class CodaExport(dict):
    """``{param: (array (chains, samples, k), labels)}`` with the coda
    mcmc-window metadata as the ``window`` attribute — (start1, end1, thin) =
    (transient + start*thin, transient + samples*thin, thin)."""

    window: tuple | None = None


def _autocov_fft(x: np.ndarray) -> np.ndarray:
    """Autocovariance per chain along axis 1 via FFT; x (chains, n, ...).

    Entries are processed in slices: the rfft intermediate is complex128 at
    ~2n points per entry, so one shot over a 10^6-entry Beta/Omega pass
    would materialise tens of GB."""
    n = x.shape[1]
    xc = x - x.mean(axis=1, keepdims=True)
    nfft = int(2 ** np.ceil(np.log2(2 * n)))
    flat = xc.reshape(x.shape[0], n, -1)
    K = flat.shape[2]
    step = max(1, int(2e8 // (x.shape[0] * nfft * 16)))   # ~200 MB complex
    out = np.empty(flat.shape, dtype=np.float64)   # keep f64 even for f32 input
    for j0 in range(0, K, step):
        f = np.fft.rfft(flat[:, :, j0:j0 + step], n=nfft, axis=1)
        out[:, :, j0:j0 + step] = np.fft.irfft(
            f * np.conj(f), n=nfft, axis=1)[:, :n]
    return out.reshape(x.shape) / n


def effective_size(x: np.ndarray) -> np.ndarray:
    """ESS over (chains, samples, ...) via Geyer's initial monotone sequence.

    Returns an array of the trailing shape.
    """
    x = np.asarray(x, dtype=float)
    if x.ndim == 1:
        x = x[None, :]
    m, n = x.shape[:2]
    acov = _autocov_fft(x)                       # (m, n, ...)
    # combine chains (rank-normalised would be arviz-style; plain mean here)
    var_w = acov[:, 0].mean(axis=0)
    rho = acov.mean(axis=0) / np.where(var_w == 0, 1.0, var_w)
    # Geyer: sum consecutive pairs while positive & monotone — vectorised
    # over entries (a full Beta/Omega ESS pass on a 1000-species model has
    # ~10^6 entries; the interpreted per-entry loop took hours there)
    trail = rho.shape[1:]
    rho2 = rho.reshape(n, -1)                    # (n, K)
    T = (n - 1) // 2                             # lag pairs (1,2),(3,4),...
    if T == 0:
        s = np.zeros(rho2.shape[1])
    else:
        P = rho2[1:2 * T + 1].reshape(T, 2, -1).sum(axis=1)   # (T, K)
        neg = P < 0
        first_neg = np.where(neg.any(axis=0), neg.argmax(axis=0), T)
        valid = np.arange(T)[:, None] < first_neg[None, :]
        # adjusted[t] = min(raw[0..t]): the monotone (non-increasing) pass
        Pm = np.minimum.accumulate(P, axis=0)
        s = np.where(valid, Pm, 0.0).sum(axis=0)
    ess = m * n / (1.0 + 2.0 * s)
    return ess.reshape(trail) if trail else float(ess[0])


def gelman_rhat(x: np.ndarray) -> np.ndarray:
    """Split-chain potential scale reduction factor (PSRF)."""
    x = np.asarray(x, dtype=float)
    m, n = x.shape[:2]
    half = n // 2
    splits = np.concatenate([x[:, :half], x[:, half:2 * half]], axis=0)
    mm, nn = splits.shape[:2]
    mean_c = splits.mean(axis=1)
    var_c = splits.var(axis=1, ddof=1)
    W = var_c.mean(axis=0)
    B = nn * mean_c.var(axis=0, ddof=1)
    var_hat = (nn - 1) / nn * W + B / nn
    with np.errstate(divide="ignore", invalid="ignore"):
        rhat = np.sqrt(var_hat / W)
    return np.where(W > 0, rhat, 1.0)


def _decorate(names, letter, flags):
    """Reference name decoration (convertToCodaObject.r:56-91): keep the raw
    name, the ``(S1)``-style number, or both per the two boolean flags."""
    out = []
    for i, n in enumerate(names):
        parts = []
        if flags[0]:
            parts.append(str(n))
        if flags[1]:
            parts.append(f"({letter}{i + 1})")
        out.append(" ".join(parts))
    return out


def convert_to_coda_object(post, start: int = 1,
                           sp_names_numbers=(True, True),
                           cov_names_numbers=(True, True),
                           tr_names_numbers=(True, True),
                           get_parameters=("Beta", "Gamma", "V", "sigma",
                                           "rho")):
    """Named per-parameter chain arrays with the reference's exact label
    formats and vec orderings (``R/convertToCodaObject.r:36-221``):

    - ``Beta``: ``B[cov, sp]``, covariate varying fastest (column-major vec);
      ``Gamma``/``V`` analogous; ``sigma`` -> ``Sig[sp]``; ``rho`` only for
      phylogenetic models.
    - per level: ``Eta{r}[unit, factor{h}]`` (units fastest),
      ``Lambda{r}``/``Psi{r}`` ``[sp, factor{h}]`` (species fastest),
      ``Alpha{r}[factor{h}]`` exported as grid *values*,
      ``Delta{r}[factor{h}]``, ``Omega{r}[sp, sp]``; factor-padded slots are
      zero-filled like the reference's cross-chain nfMax padding (:173-218).
    - ``start`` drops the first ``start-1`` recorded samples per chain
      (reference ``postList[start:...]``); the returned :class:`CodaExport`
      carries the mcmc-window metadata as its ``window`` attribute.
    - raises if the factor count changed within a chain's selected window
      (reference :168-169) — thin past the adaptation phase instead.

    Returns a :class:`CodaExport`:
    ``{param: (array (chains, kept_samples, k), labels)}``.
    """
    hM, spec = post.hM, post.spec
    sp = _decorate(hM.sp_names, "S", sp_names_numbers)
    cov = _decorate(hM.cov_names, "C", cov_names_numbers)
    tr = _decorate(hM.tr_names, "T", tr_names_numbers)
    sel = slice(start - 1, None)

    out = CodaExport()
    out.window = (post.transient + start * post.thin,
                  post.transient + post.samples * post.thin, post.thin)
    for par in get_parameters:
        if par not in post.arrays:
            continue
        if par == "rho" and not spec.has_phylo:
            continue                               # reference :40-42
        a = post.arrays[par][:, sel]
        if par in ("Beta", "Gamma", "V"):
            # column-major vec: first index (covariate) varying fastest
            flat = a.transpose(0, 1, 3, 2).reshape(a.shape[:2] + (-1,))
            second = {"Beta": sp, "Gamma": tr, "V": cov}[par]
            tag = {"Beta": "B", "Gamma": "G", "V": "V"}[par]
            labels = [f"{tag}[{c}, {s}]" for s in second for c in cov]
        elif par == "sigma":
            flat = a.reshape(a.shape[:2] + (-1,))
            labels = [f"Sig[{s}]" for s in sp]
        elif par == "rho":                         # scalar grid value
            flat = a.reshape(a.shape[:2] + (-1,))
            labels = ["Rho"]
        elif par in ("wRRR", "PsiRRR"):
            # (c, s, nc_rrr, nc_orrr): component varying fastest, like Beta's
            # column-major vec; original-covariate names when known
            flat = a.transpose(0, 1, 3, 2).reshape(a.shape[:2] + (-1,))
            comp = [f"XRRR_{k + 1}" for k in range(a.shape[2])]
            onames = getattr(hM, "xrrr_names", None) \
                or [f"XRRRcov_{j + 1}" for j in range(a.shape[3])]
            ocov = _decorate(onames, "C", cov_names_numbers)
            labels = [f"{par}[{c}, {o}]" for o in ocov for c in comp]
        elif par == "DeltaRRR":
            flat = a.reshape(a.shape[:2] + (-1,))
            labels = [f"DeltaRRR[XRRR_{k + 1}]" for k in range(flat.shape[2])]
        else:                                      # generic numbered fallback
            flat = a.reshape(a.shape[:2] + (-1,))
            labels = [f"{par}[{i + 1}]" for i in range(flat.shape[2])]
        out[par] = (flat, labels)

    for r in range(spec.nr):
        mask = post.arrays[f"nfMask_{r}"][:, sel]  # (c, s, nf_max)
        nf_per = mask.sum(axis=2)
        if (nf_per != nf_per[:, :1]).any():
            raise ValueError("HMSC: number of latent factors was changing "
                             "in selected sequence of samples")
        units = hM.ranLevels[r].pi
        nf_max = mask.shape[2]
        facs = [f"factor{h + 1}" for h in range(nf_max)]

        # record=-restricted runs may lack some level parameters; export
        # whichever were recorded
        if f"Eta_{r}" in post.arrays:
            eta = post.arrays[f"Eta_{r}"][:, sel] * mask[:, :, None, :]
            out[f"Eta_{r}"] = (
                eta.transpose(0, 1, 3, 2).reshape(eta.shape[:2] + (-1,)),
                [f"Eta{r + 1}[{u}, {f}]" for f in facs for u in units])

        if f"Lambda_{r}" in post.arrays:
            lam = post.arrays[f"Lambda_{r}"][:, sel]
            lam = lam[..., 0] if lam.ndim == 5 else lam
            out[f"Lambda_{r}"] = (
                lam.reshape(lam.shape[:2] + (-1,)),
                [f"Lambda{r + 1}[{s}, {f}]" for f in facs for s in sp])

            om = np.einsum("csfj,csfk->csjk", lam, lam)
            out[f"Omega_{r}"] = (
                om.reshape(om.shape[:2] + (-1,)),
                [f"Omega{r + 1}[{a_}, {b}]" for b in sp for a_ in sp])

        if f"Psi_{r}" in post.arrays:
            psi = post.arrays[f"Psi_{r}"][:, sel]
            psi = psi[..., 0] if psi.ndim == 5 else psi
            psi = psi * mask[:, :, :, None]
            out[f"Psi_{r}"] = (
                psi.reshape(psi.shape[:2] + (-1,)),
                [f"Psi{r + 1}[{s}, {f}]" for f in facs for s in sp])

        if f"Delta_{r}" in post.arrays:
            delta = post.arrays[f"Delta_{r}"][:, sel]
            delta = delta[..., 0] if delta.ndim == 4 else delta
            out[f"Delta_{r}"] = (
                delta * mask,
                [f"Delta{r + 1}[{f}]" for f in facs])

        if f"Alpha_{r}" in post.arrays:
            alpha = post.arrays[f"Alpha_{r}"][:, sel]
            if spec.levels[r].spatial is not None:
                vals = np.asarray(hM.ranLevels[r].alphapw)[:, 0]
                alpha = vals[alpha] * mask
            else:
                alpha = alpha * mask
            out[f"Alpha_{r}"] = (
                alpha, [f"Alpha{r + 1}[{f}]" for f in facs])
    return out
