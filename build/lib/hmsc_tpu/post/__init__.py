from .posterior import Posterior, pool_mcmc_chains
from .diagnostics import effective_size, gelman_rhat, convert_to_coda_object
from .associations import compute_associations
from .align import align_posterior
from .metrics import (evaluate_model_fit, compute_waic,
                      compute_variance_partitioning)

__all__ = ["Posterior", "pool_mcmc_chains", "effective_size", "gelman_rhat",
           "convert_to_coda_object", "compute_associations", "align_posterior",
           "evaluate_model_fit", "compute_waic",
           "compute_variance_partitioning"]
