"""Residual species-association summaries (reference
``R/computeAssociations.R:19-39``): per random level the posterior mean of
cov2cor(Lambda' Lambda) and the support P(omega > 0), as one batched einsum
over the whole posterior."""

from __future__ import annotations

import numpy as np

__all__ = ["compute_associations"]


def compute_associations(post, start: int = 0, thin: int = 1):
    # per-chain windowing like the reference's poolMcmcChains(start, thin)
    # (slicing the pooled chain-concatenated axis would thin across chain
    # boundaries)
    post = post.subset(start, thin)
    out = []
    for r in range(post.spec.nr):
        lam = post.pooled(f"Lambda_{r}")                  # (n, nf, ns[, ncr])
        lam = lam[..., 0] if lam.ndim == 4 else lam
        om = np.einsum("nfj,nfk->njk", lam, lam)
        d = np.sqrt(np.maximum(np.einsum("njj->nj", om), 1e-30))
        cor = om / d[:, :, None] / d[:, None, :]
        out.append({
            "mean": cor.mean(axis=0),
            "support": (om > 0).mean(axis=0),
        })
    return out
