"""Model-assessment metrics (L4): ``evaluate_model_fit``, ``compute_waic``,
``compute_variance_partitioning`` (reference ``R/evaluateModelFit.R:53-169``,
``R/computeWAIC.R:25-131``, ``R/computeVariancePartitioning.R:37-205``).

All three recompute per-draw quantities the reference obtains by interpreted
per-sample R loops; here the whole pooled posterior is one stacked batch and
every reduction is a vectorised einsum / elementwise pass (SURVEY.md §3.5).
AUC is the rank-based Mann-Whitney statistic (equals the reference's
``pROC::auc``); Poisson WAIC terms use Gauss-Hermite quadrature over the
lognormal mixing exactly like the reference.
"""

from __future__ import annotations

import numpy as np

__all__ = ["evaluate_model_fit", "compute_waic",
           "compute_variance_partitioning"]


# ---------------------------------------------------------------------------
# shared: linear predictor over the whole pooled posterior
# ---------------------------------------------------------------------------

def posterior_linear_predictor(post) -> np.ndarray:
    """(n_draws, ny, ns) linear predictor at the training design from the
    recorded (back-transformed) posterior: L = X B + sum_r Eta_r[Pi_r] Lam_r.
    Delegates to the prediction layer's batched assembly so the two stay in
    lockstep."""
    from ..predict.predict import _lin_pred

    hM, spec = post.hM, post.spec
    eta_pred = [post.pooled(f"Eta_{r}") for r in range(hM.nr)]
    pi = [hM.Pi[:, r] for r in range(hM.nr)]
    x_row = [hM.ranLevels[r].x_for(hM.pi_names[r])[hM.Pi[:, r]]
             if hM.ranLevels[r].x_dim > 0 else np.ones((hM.ny, 1))
             for r in range(hM.nr)]
    return _lin_pred(hM, spec, hM.X, hM.x_is_list,
                     hM.XRRR if hM.nc_rrr > 0 else None, post,
                     post.pooled("Beta"), eta_pred, pi, x_row)


# ---------------------------------------------------------------------------
# evaluateModelFit
# ---------------------------------------------------------------------------

def _rmse(Y, P):
    return np.sqrt(np.nanmean((Y - P) ** 2, axis=0))


def _pearson_r2(Y, P):
    out = np.full(Y.shape[1], np.nan)
    for j in range(Y.shape[1]):
        m = ~np.isnan(Y[:, j]) & ~np.isnan(P[:, j])
        if m.sum() > 1 and np.std(Y[m, j]) > 0 and np.std(P[m, j]) > 0:
            co = np.corrcoef(Y[m, j], P[m, j])[0, 1]
            out[j] = np.sign(co) * co**2
    return out


def _rank(x):
    order = np.argsort(x, kind="mergesort")
    ranks = np.empty(len(x))
    ranks[order] = np.arange(1, len(x) + 1)
    # midranks for ties
    sx = x[order]
    i = 0
    while i < len(x):
        j = i
        while j + 1 < len(x) and sx[j + 1] == sx[i]:
            j += 1
        if j > i:
            ranks[order[i:j + 1]] = (i + j) / 2 + 1
        i = j + 1
    return ranks


def _spearman_r2(Y, P):
    out = np.full(Y.shape[1], np.nan)
    for j in range(Y.shape[1]):
        m = ~np.isnan(Y[:, j]) & ~np.isnan(P[:, j])
        if m.sum() > 1:
            ry, rp = _rank(Y[m, j]), _rank(P[m, j])
            if np.std(ry) > 0 and np.std(rp) > 0:
                co = np.corrcoef(ry, rp)[0, 1]
                out[j] = np.sign(co) * co**2
    return out


def _auc(Y, P):
    """Mann-Whitney AUC per species (== pROC::auc with direction '<')."""
    out = np.full(Y.shape[1], np.nan)
    for j in range(Y.shape[1]):
        m = ~np.isnan(Y[:, j]) & ~np.isnan(P[:, j])
        y = (Y[m, j] > 0).astype(int)
        n1, n0 = y.sum(), (1 - y).sum()
        if n1 == 0 or n0 == 0:
            continue
        r = _rank(P[m, j])
        out[j] = (r[y == 1].sum() - n1 * (n1 + 1) / 2) / (n1 * n0)
    return out


def _tjur_r2(Y, P):
    out = np.full(Y.shape[1], np.nan)
    for j in range(Y.shape[1]):
        m = ~np.isnan(Y[:, j])
        y, p = Y[m, j], P[m, j]
        if (y == 1).any() and (y == 0).any():
            out[j] = np.nanmean(p[y == 1]) - np.nanmean(p[y == 0])
    return out


def evaluate_model_fit(hM, predY: np.ndarray) -> dict:
    """Per-species fit metrics from a (n_draws, ny, ns) prediction array
    (reference ``evaluateModelFit.R:53-169``): RMSE always; normal -> signed
    pearson R2; probit -> AUC + Tjur R2; Poisson -> spearman pseudo-R2 plus
    occurrence-truncated (O.*) and conditional-on-presence (C.*) variants."""
    predY = np.asarray(predY)
    fam = hM.distr[:, 0]
    mPredY = np.empty((hM.ny, hM.ns))
    sel_p = fam == 3
    if sel_p.any():
        mPredY[:, sel_p] = np.nanmedian(predY[:, :, sel_p], axis=0)
    if (~sel_p).any():
        mPredY[:, ~sel_p] = np.nanmean(predY[:, :, ~sel_p], axis=0)

    MF = {"RMSE": _rmse(hM.Y, mPredY)}
    sel = fam == 1
    if sel.any():
        R2 = np.full(hM.ns, np.nan)
        R2[sel] = _pearson_r2(hM.Y[:, sel], mPredY[:, sel])
        MF["R2"] = R2
    sel = fam == 2
    if sel.any():
        AUC = np.full(hM.ns, np.nan)
        Tjur = np.full(hM.ns, np.nan)
        AUC[sel] = _auc(hM.Y[:, sel], mPredY[:, sel])
        Tjur[sel] = _tjur_r2(hM.Y[:, sel], mPredY[:, sel])
        MF["AUC"] = AUC
        MF["TjurR2"] = Tjur
    sel = fam == 3
    if sel.any():
        SR2 = np.full(hM.ns, np.nan)
        SR2[sel] = _spearman_r2(hM.Y[:, sel], mPredY[:, sel])
        MF["SR2"] = SR2
        predO = (predY[:, :, sel] > 0).astype(float)
        mPredO = np.nanmean(predO, axis=0)
        YO = (hM.Y[:, sel] > 0).astype(float)
        YO[np.isnan(hM.Y[:, sel])] = np.nan
        for name, arr in (("O.AUC", _auc(YO, mPredO)),
                          ("O.TjurR2", _tjur_r2(YO, mPredO)),
                          ("O.RMSE", _rmse(YO, mPredO))):
            full = np.full(hM.ns, np.nan)
            full[sel] = arr
            MF[name] = full
        with np.errstate(divide="ignore", invalid="ignore"):
            mPredC = mPredY[:, sel] / mPredO
        CY = hM.Y[:, sel].copy()
        CY[CY == 0] = np.nan
        for name, arr in (("C.SR2", _spearman_r2(CY, mPredC)),
                          ("C.RMSE", _rmse(CY, mPredC))):
            full = np.full(hM.ns, np.nan)
            full[sel] = arr
            MF[name] = full
    return MF


# ---------------------------------------------------------------------------
# computeWAIC
# ---------------------------------------------------------------------------

def compute_waic(post, ghN: int = 11) -> float:
    """WAIC from pointwise log-likelihoods over the pooled posterior
    (reference ``computeWAIC.R:25-131``): exact for normal & probit, Poisson
    via ``ghN``-point Gauss-Hermite quadrature over the lognormal mixing.
    The lppd term uses a stable log-mean-exp over draws."""
    from scipy.special import log_ndtr

    hM = post.hM
    E = posterior_linear_predictor(post)             # (n, ny, ns)
    sigma = post.pooled("sigma")                     # (n, ns)
    fam = hM.distr[:, 0]
    Y = hM.Y
    na = np.isnan(Y)
    n_draws = E.shape[0]

    L = np.zeros((n_draws, hM.ny))
    sel = fam == 1
    if sel.any():
        sd = np.sqrt(sigma[:, None, sel])
        t = (-0.5 * np.log(2 * np.pi) - np.log(sd)
             - 0.5 * ((Y[None, :, sel] - E[:, :, sel]) / sd) ** 2)
        t[:, na[:, sel]] = 0.0
        L += t.sum(axis=2)
    sel = fam == 2
    if sel.any():
        # unit-sd probit log-lik, like the reference (computeWAIC.R:97-99);
        # Y is 0/1 so select between the two tails rather than multiplying
        # two (n, ny, ns)-sized products
        Ey = E[:, :, sel]
        t = np.where(Y[None, :, sel] > 0.5, log_ndtr(Ey), log_ndtr(-Ey))
        t[:, na[:, sel]] = 0.0
        L += t.sum(axis=2)
    sel = fam == 3
    if sel.any():
        from scipy.special import gammaln

        gx, gw = np.polynomial.hermite.hermgauss(ghN)
        std = np.sqrt(sigma[:, None, sel])
        gX = (E[:, :, sel, None]
              + np.sqrt(2.0) * gx[None, None, None, :] * std[..., None])
        lam = np.exp(np.clip(gX, None, 30.0))
        yv = Y[None, :, sel, None]
        log_pois = yv * gX - lam - gammaln(np.where(na[:, sel], 0, Y[:, sel])[None, :, :, None] + 1.0)
        # integrate exp(log_pois) against the GH weights, in log space
        mx = log_pois.max(axis=-1, keepdims=True)
        integral = np.log((np.exp(log_pois - mx) * gw[None, None, None, :]
                           ).sum(axis=-1)) + mx[..., 0] - 0.5 * np.log(np.pi)
        integral[:, na[:, sel]] = 0.0
        L += integral.sum(axis=2)

    # WAIC = mean over units of (-log mean_n lik) + var_n(log lik)
    mx = L.max(axis=0, keepdims=True)
    lppd_neg = -(np.log(np.exp(L - mx).mean(axis=0)) + mx[0])
    V = L.var(axis=0, ddof=1)
    return float(np.mean(lppd_neg + V))


# ---------------------------------------------------------------------------
# computeVariancePartitioning
# ---------------------------------------------------------------------------

def compute_variance_partitioning(post, group=None, group_names=None,
                                  start: int = 0,
                                  na_ignore: bool = False) -> dict:
    """Per-species variance shares of each covariate group and random level,
    plus trait-explained R2 (reference ``computeVariancePartitioning.R``).
    All per-draw quantities are batched einsums over the pooled posterior."""
    hM = post.hM
    ns, nc, nr = hM.ns, hM.nc, hM.nr
    if group is None:
        if nc > 1:
            group = np.concatenate([[1], np.arange(1, nc)])
            group_names = list(hM.cov_names[1:nc])
        else:
            group = np.array([1])
            group_names = list(hM.cov_names[:1])
    group = np.asarray(group, dtype=int)
    if group.size != nc:
        raise ValueError(
            f"computeVariancePartitioning: group must assign one of ngroups "
            f"to each of the nc={nc} covariates")
    if group.min() < 1:
        raise ValueError(
            "computeVariancePartitioning: group labels are 1-indexed "
            "(reference convention); got a label < 1")
    ngroups = int(group.max())
    missing = set(range(1, ngroups + 1)) - set(group.tolist())
    if missing:
        raise ValueError(
            "computeVariancePartitioning: group labels must be contiguous "
            f"1..{ngroups}; no covariate is assigned to group(s) "
            f"{sorted(missing)}")
    if group_names is not None and len(group_names) != ngroups:
        raise ValueError(
            f"computeVariancePartitioning: groupnames has "
            f"{len(group_names)} entries but group defines {ngroups} groups")

    # per-chain windowing like the reference's poolMcmcChains(start)
    post = post.subset(start)
    Beta = post.pooled("Beta")                       # (n, nc, ns)
    Gamma = post.pooled("Gamma")                     # (n, nc, nt)
    n_draws = Beta.shape[0]

    X2 = hM.X if not hM.x_is_list else None
    if na_ignore or hM.x_is_list:
        # per-species covariance of X over that species' informative rows
        cM = np.empty((ns, nc, nc))
        for j in range(ns):
            Xj = hM.X[j] if hM.x_is_list else hM.X
            rows = ~np.isnan(hM.Y[:, j]) if na_ignore else np.ones(hM.ny, bool)
            cM[j] = np.cov(Xj[rows], rowvar=False)
    else:
        cM = np.broadcast_to(np.cov(X2, rowvar=False).reshape(1, nc, nc),
                             (ns, nc, nc))

    # fixed-effect variance per species, total and per covariate group
    fixed1 = np.einsum("ncj,jcd,ndj->nj", Beta, cM, Beta)       # (n, ns)
    fixedsplit1 = np.empty((n_draws, ns, ngroups))
    for k in range(ngroups):
        s = group == k + 1
        fixedsplit1[:, :, k] = np.einsum("ncj,jcd,ndj->nj", Beta[:, s],
                                         cM[np.ix_(range(ns), s, s)],
                                         Beta[:, s])
    # random-level variance per species: sum_h lambda_h^2.  For a
    # covariate-dependent level the per-unit variance is (lambda_h' x_u)^2,
    # so average over units: lambda_h' E[x x'] lambda_h.  (The reference's
    # own xDim>0 line `t(Lambda[factor,])*Lambda[factor,]` is shape-invalid
    # R, computeVariancePartitioning.R:159 — this is the intended quantity.)
    random1 = np.empty((n_draws, ns, nr))
    for r in range(nr):
        lam = post.pooled(f"Lambda_{r}")
        if lam.ndim == 4 and lam.shape[-1] > 1:
            xu = hM.ranLevels[r].x_for(hM.pi_names[r])
            M2 = xu.T @ xu / xu.shape[0]                   # (ncr, ncr)
            random1[:, :, r] = np.einsum("nhjk,kl,nhjl->nj", lam, M2, lam)
        else:
            lam = lam[..., 0] if lam.ndim == 4 else lam
            random1[:, :, r] = (lam**2).sum(axis=1)

    if nr > 0:
        tot = fixed1 + random1.sum(axis=2)
        fixed = (fixed1 / tot).mean(axis=0)
        random = (random1 / tot[:, :, None]).mean(axis=0)
    else:
        fixed = np.ones(ns)
        random = np.zeros((ns, 0))
    denom = fixedsplit1.sum(axis=2, keepdims=True)
    fixedsplit = (fixedsplit1 / np.where(denom > 0, denom, 1.0)).mean(axis=0)

    # trait R2: correlation between Beta and its trait-implied mean
    # Tr (ns, nt), Gamma (n, nc, nt) -> Mu (n, nc, ns)
    Mu = np.einsum("jt,nct->ncj", hM.Tr, Gamma)
    R2T_Beta = np.zeros(nc)
    for k in range(nc):
        b, m = Beta[:, k, :], Mu[:, k, :]
        bc = b - b.mean(axis=1, keepdims=True)
        mc = m - m.mean(axis=1, keepdims=True)
        num = (bc * mc).sum(axis=1)
        den = np.sqrt((bc**2).sum(axis=1) * (mc**2).sum(axis=1))
        co = np.where(den > 0, num / np.where(den > 0, den, 1.0), 0.0)
        R2T_Beta[k] = float(np.mean(co**2))

    # trait R2 for Y: per draw, across-species covariance of the fitted
    # linear predictors vs the trait-implied ones (computeVariancePartitioning.R:125-143)
    if hM.x_is_list:
        f = np.einsum("jyc,ncj->nyj", hM.X, Beta)
        a = np.einsum("jyc,ncj->nyj", hM.X, Mu)
    else:
        f = np.einsum("yc,ncj->nyj", hM.X, Beta)
        a = np.einsum("yc,ncj->nyj", hM.X, Mu)
    a = a - a.mean(axis=2, keepdims=True)
    f = f - f.mean(axis=2, keepdims=True)
    res1 = (((a * f).sum(axis=2) / (ns - 1)) ** 2).sum(axis=1)
    res2 = (((a * a).sum(axis=2) / (ns - 1))
            * ((f * f).sum(axis=2) / (ns - 1))).sum(axis=1)
    R2T_Y = float(np.mean(res1 / np.where(res2 > 0, res2, 1.0)))

    vals = np.zeros((ngroups + nr, ns))
    for k in range(ngroups):
        vals[k] = fixed * fixedsplit[:, k]
    for r in range(nr):
        vals[ngroups + r] = random[:, r]
    leg = list(group_names or [f"group{k+1}" for k in range(ngroups)])
    leg += [f"Random: {hM.rl_names[r]}" for r in range(nr)]
    return {"vals": vals, "R2T": {"Beta": R2T_Beta, "Y": R2T_Y},
            "group": group, "groupnames": leg[:ngroups], "names": leg}
