"""Precomputation layer (reference ``R/computeDataParameters.R``,
``R/computeInitialParameters.R``, ``R/constructKnots.R``).

Host-side, numpy/f64.  Two deliberate TPU-first departures from the reference:

- **Phylogeny**: instead of materialising ns x ns x 101 arrays of
  Q(rho)-cholesky/inverse/determinant grids (2.4 TB at ns=1000), we store one
  eigendecomposition C = U diag(d) U'.  Every grid matrix Q(rho) shares U, so
  its eigenvalues, inverse, and log-determinant are O(ns) arithmetic on d
  (SURVEY.md §7 point 2).  Negative rho (Q = -rho C^{-1} + (1+rho) I,
  reference computeDataParameters.R:30-33) shares the same eigenvectors.
- **NNGP**: the sparse Vecchia factors are stored as dense neighbour-index /
  coefficient arrays (np x k), not sparse matrices — gathers + batched small
  solves are the TPU idiom (SURVEY.md §2.4).
"""

from __future__ import annotations

import numpy as np
from scipy.spatial import cKDTree

from .model import FIXED_SIGMA2, Hmsc

__all__ = ["compute_data_parameters", "compute_initial_parameters",
           "construct_knots", "DataParams", "LevelParams"]


class LevelParams:
    """Spatial grids for one random level (length-G arrays over alphapw)."""

    def __init__(self, **kw):
        self.__dict__.update(kw)


class DataParams:
    """Phylogeny eigensystem + per-level spatial grids."""

    def __init__(self, U=None, d=None, Qeig=None, logdetQ=None, rL_par=None):
        self.U = U                  # (ns, ns) eigenvectors of C
        self.d = d                  # (ns,) eigenvalues of C
        self.Qeig = Qeig            # (G_rho, ns) eigenvalues of Q(rho_g)
        self.logdetQ = logdetQ      # (G_rho,)
        self.rL_par = rL_par or []  # list[LevelParams | None]


def _rho_eigvals(rho: np.ndarray, d: np.ndarray) -> np.ndarray:
    """Eigenvalues of Q(rho) = rho*C + (1-rho)*I (rho>=0) or
    -rho*C^{-1} + (1+rho)*I (rho<0), in C's eigenbasis."""
    rho = rho[:, None]
    pos = rho * d[None, :] + (1.0 - rho)
    neg = (-rho) / d[None, :] + (1.0 + rho)
    return np.where(rho >= 0, pos, neg)


def compute_data_parameters(hM: Hmsc) -> DataParams:
    """Phylogeny eigendecomposition and per-level spatial grids."""
    par = DataParams()
    if hM.C is not None:
        d, U = np.linalg.eigh(hM.C)
        # clip tiny negative eigenvalues from near-singular trees
        d = np.clip(d, 1e-8, None)
        par.U, par.d = U, d
        # Floor the Q(rho) eigenvalues at 1e-4: the engine consumes them as
        # 1/e in f32 quadratic forms, and for near-singular C only the
        # rho=1 grid endpoint is affected (min eig = (1-rho) + rho*d_min).
        # The log-dets are recomputed from the floored values so the rho
        # grid posterior stays internally consistent (SURVEY.md §7.6).
        par.Qeig = np.maximum(_rho_eigvals(hM.rhopw[:, 0], d), 1e-4)
        par.logdetQ = np.sum(np.log(par.Qeig), axis=1)

    par.rL_par = []
    for r in range(hM.nr):
        rL = hM.ranLevels[r]
        if rL.s_dim == 0:
            par.rL_par.append(None)
            continue
        units = hM.pi_names[r]
        alphapw = rL.alphapw
        method = rL.spatial_method
        if method == "Full":
            if rL.dist_mat is None:
                s = rL.coords_for(units)
                dd = s[:, None, :] - s[None, :, :]
                distance = np.sqrt((dd**2).sum(-1))
            else:
                distance = rL.dist_for(units)
            par.rL_par.append(_full_grids(distance, alphapw[:, 0]))
        elif method == "NNGP":
            if rL.dist_mat is not None:
                raise ValueError("computeDataParameters: Nearest neighbours not available for distance matrices")
            k = rL.n_neighbours or 10
            s = rL.coords_for(units)
            par.rL_par.append(_nngp_grids(s, k, alphapw[:, 0]))
        elif method == "GPP":
            if rL.dist_mat is not None:
                raise ValueError("computeDataParameters: predictive gaussian process not available for distance matrices")
            s = rL.coords_for(units)
            knots = rL.s_knot
            if knots is None:
                raise ValueError("computeDataParameters: GPP requires knot locations (sKnot)")
            par.rL_par.append(_gpp_grids(s, np.asarray(knots, float), alphapw[:, 0]))
        else:  # pragma: no cover
            raise ValueError(f"unknown spatial method {method}")
    return par


def _full_grids(distance: np.ndarray, alphas: np.ndarray) -> LevelParams:
    """Exact-GP grids: iW(alpha) and log det W(alpha) per grid point
    (reference computeDataParameters.R:54-81).  W(alpha=0) = I."""
    n = distance.shape[0]
    G = len(alphas)
    iWg = np.empty((G, n, n))
    detWg = np.empty(G)
    for g, a in enumerate(alphas):
        W = np.eye(n) if a == 0 else np.exp(-distance / a)
        L = np.linalg.cholesky(W + 1e-10 * np.eye(n))
        Li = np.linalg.inv(L)
        iWg[g] = Li.T @ Li
        detWg[g] = 2.0 * np.sum(np.log(np.diag(L)))
    return LevelParams(kind="Full", iWg=iWg, detWg=detWg, distance=distance)


def _nngp_grids(s: np.ndarray, k: int, alphas: np.ndarray) -> LevelParams:
    """Vecchia / NNGP factors as dense (np, k) neighbour arrays.

    Matches the reference's construction (computeDataParameters.R:82-136):
    kNN graph over all points, neighbours restricted to lower indices; per
    alpha, per point: small kriging solve against its neighbours giving the
    autoregressive coefficients A[i] and conditional variance D[i], so
    RiW = D^{-1/2} (I - A) and iW = RiW' RiW.
    """
    n = s.shape[0]
    k = min(k, n - 1)
    tree = cKDTree(s)
    _, idx = tree.query(s, k=k + 1)
    nn = np.sort(idx[:, 1:], axis=1)             # drop self, ascending order

    # neighbour lists restricted to prior points, padded
    nn_idx = np.zeros((n, k), dtype=np.int64)
    nn_n = np.zeros(n, dtype=np.int64)
    for i in range(1, n):
        prev = nn[i][nn[i] < i]
        nn_n[i] = len(prev)
        nn_idx[i, :len(prev)] = prev

    G = len(alphas)
    coef = np.zeros((G, n, k))
    D = np.ones((G, n))
    detWg = np.zeros(G)
    pad_mask = np.arange(k)[None, :] < nn_n[:, None]
    # pairwise distances point<->neighbours and neighbour<->neighbour
    d_in = np.sqrt(((s[:, None, :] - s[nn_idx]) ** 2).sum(-1))        # (n, k)
    d_nn = np.sqrt(((s[nn_idx][:, :, None, :] - s[nn_idx][:, None, :, :]) ** 2).sum(-1))  # (n,k,k)
    for g, a in enumerate(alphas):
        if a == 0:
            continue  # iW = I, detW = 0
        Knn = np.exp(-d_nn / a)
        kin = np.exp(-d_in / a)
        # mask out padding: identity rows/cols, zero rhs
        m2 = pad_mask[:, :, None] & pad_mask[:, None, :]
        Knn = np.where(m2, Knn, np.eye(k)[None])
        kin = np.where(pad_mask, kin, 0.0)
        v = np.linalg.solve(Knn + 1e-10 * np.eye(k)[None], kin[..., None])[..., 0]
        v = np.where(pad_mask, v, 0.0)
        Dg = 1.0 - (kin * v).sum(-1)
        # same coincidence hazard as the GPP grids: duplicate unit
        # coordinates give conditional variance 0, so 1/D and log(D) blow
        # up in the f32 quadratics / CG scalings
        Dg = np.maximum(Dg, _GP_DD_FLOOR)
        Dg[0] = 1.0
        coef[g] = v
        D[g] = Dg
        detWg[g] = np.sum(np.log(Dg))
    return LevelParams(kind="NNGP", nn_idx=nn_idx, nn_coef=coef, nn_D=D,
                       detWg=detWg, s=s)


# conditional-variance floor for the GPP and NNGP grids (see the comments at
# the use sites; module-level so the coincidence regression tests can probe
# values).  1e-3 of the unit marginal variance: measured stable over 4
# chains at the knot-coincident GPP regression config (1e-4 still diverged
# in f32)
_GP_DD_FLOOR = 1e-3


def _gpp_grids(s: np.ndarray, knots: np.ndarray, alphas: np.ndarray) -> LevelParams:
    """Knot-based predictive-process grids (reference
    computeDataParameters.R:138-194): per alpha the diagonal residual
    correction idD, idD*W12, F = W22 + W12' idD W12, its inverse, and
    log det of the implied covariance."""
    n, nK = s.shape[0], knots.shape[0]
    d12 = np.sqrt(((s[:, None, :] - knots[None, :, :]) ** 2).sum(-1))
    dd = knots[:, None, :] - knots[None, :, :]
    d22 = np.sqrt((dd**2).sum(-1))
    G = len(alphas)
    idDg = np.empty((G, n))
    idDW12g = np.empty((G, n, nK))
    Fg = np.empty((G, nK, nK))
    iFg = np.empty((G, nK, nK))
    detDg = np.empty(G)
    for g, a in enumerate(alphas):
        if a == 0:
            W22 = np.eye(nK)
            W12 = np.zeros((n, nK))
        else:
            W22 = np.exp(-d22 / a)
            W12 = np.exp(-d12 / a)
        iW22 = np.linalg.inv(W22 + 1e-10 * np.eye(nK))
        dD = 1.0 - np.einsum("ik,kl,il->i", W12, iW22, W12)
        # nugget floor: a unit placed AT (or within float distance of) a
        # knot has conditional variance dD -> 0, so idD = 1/dD explodes and
        # the f32 double-Woodbury Eta solve cancels catastrophically
        # (measured: knots taken from the data locations give idD ~ 1e10
        # and the chain diverges at sweep 1).  The floor is far below any
        # realistic residual scale and keeps the on-device algebra within
        # f32 range.  (The reference divides by dD with no guard and would
        # produce Inf on exact coincidence, computeDataParameters.R:138-194.)
        dD = np.maximum(dD, _GP_DD_FLOOR)
        idD = 1.0 / dD
        idDW12 = idD[:, None] * W12
        F = W22 + W12.T @ idDW12
        iF = np.linalg.inv(F)
        # log det of W_gpp = W12 iW22 W12' + diag(dD)
        liW22 = np.linalg.cholesky(iW22)
        t2 = W12 @ liW22
        DS = t2.T @ (idD[:, None] * t2) + np.eye(nK)
        LDS = np.linalg.cholesky(DS)
        detDg[g] = np.sum(np.log(dD)) + 2.0 * np.sum(np.log(np.diag(LDS)))
        idDg[g] = idD
        idDW12g[g] = idDW12
        Fg[g] = F
        iFg[g] = iF
    return LevelParams(kind="GPP", idDg=idDg, idDW12g=idDW12g, Fg=Fg, iFg=iFg,
                       detDg=detDg, s=s, knots=knots)


def construct_knots(s_data, n_knots: int | None = None, knot_dist: float | None = None,
                    min_knot_dist: float | None = None) -> np.ndarray:
    """Regular knot grid over the data's bounding box for GPP, dropping knots
    farther than ``min_knot_dist`` from any datum (reference
    ``R/constructKnots.R:26-49``)."""
    s = np.asarray(s_data, dtype=float)
    if s.ndim == 1:
        s = s[:, None]
    lo, hi = s.min(axis=0), s.max(axis=0)
    if knot_dist is not None:
        axes = [np.arange(l, h + knot_dist, knot_dist) for l, h in zip(lo, hi)]
    else:
        n_knots = n_knots or 10
        axes = [np.linspace(l, h, n_knots) for l, h in zip(lo, hi)]
    mesh = np.meshgrid(*axes, indexing="ij")
    knots = np.column_stack([m.ravel() for m in mesh])
    if min_knot_dist is None and knot_dist is not None:
        min_knot_dist = knot_dist
    if min_knot_dist is not None:
        tree = cKDTree(s)
        dist, _ = tree.query(knots, k=1)
        knots = knots[dist <= min_knot_dist]
    return knots


# ---------------------------------------------------------------------------
# initial state (reference computeInitialParameters.R:17-273)
# ---------------------------------------------------------------------------

def compute_initial_parameters(hM: Hmsc, nf_max_static, rng: np.random.Generator,
                               init_par: dict | None = None) -> dict:
    """Draw one chain's initial parameter values (host-side numpy).

    ``init_par`` may override any of Beta/Gamma/V/sigma/Lambda/Eta/Psi/Delta/
    Alpha/rho per the reference contract; ``init_par="fixed effects"`` fits
    per-species GLMs first (reference :52-79).
    Factor arrays are allocated at the static nf_max with the first nf_min
    slots active (masked factor adaptation, SURVEY.md §7 point 1).
    """
    from scipy import stats as sps

    init_par = init_par if init_par is not None else {}
    fixed_effects = init_par == "fixed effects"
    if fixed_effects:
        init_par = {}

    out: dict = {}
    ns, nc, nt, nr = hM.ns, hM.nc, hM.nt, hM.nr

    if fixed_effects:
        Beta = _fixed_effects_beta(hM, rng)
        Gamma = np.linalg.lstsq(hM.TrScaled, Beta.T, rcond=None)[0].T
        E = Beta - Gamma @ hM.TrScaled.T
        V = np.cov(E) + np.eye(nc) if nc > 1 else np.atleast_2d(np.var(E) + 1.0)
        V = np.nan_to_num(V, nan=0.0) + 0.0
    else:
        Gamma = init_par.get("Gamma")
        if Gamma is None:
            # column-major vec(Gamma) convention, matching update_gamma_v and
            # the reference (updateGammaV.R:30-32)
            Gamma = rng.multivariate_normal(hM.mGamma, hM.UGamma).reshape(
                (nc, nt), order="F")
        V = init_par.get("V")
        if V is None:
            V = sps.invwishart.rvs(df=hM.f0, scale=hM.V0, random_state=rng)
            V = np.atleast_2d(V)
        Beta = init_par.get("Beta")
        if Beta is None:
            Mu = Gamma @ hM.TrScaled.T
            Beta = Mu + np.linalg.cholesky(V) @ rng.standard_normal((nc, ns))
    out["Gamma"], out["V"], out["Beta"] = Gamma, np.atleast_2d(V), Beta

    out["BetaSel"] = [rng.uniform(size=len(sel.q)) < sel.q for sel in hM.x_select]

    if hM.nc_rrr > 0:
        DeltaRRR = np.concatenate([rng.gamma(hM.a1RRR, 1 / hM.b1RRR, 1),
                                   rng.gamma(hM.a2RRR, 1 / hM.b2RRR, hM.nc_rrr - 1)])
        PsiRRR = rng.gamma(hM.nuRRR / 2, 2 / hM.nuRRR, (hM.nc_rrr, hM.nc_orrr))
        tau = np.cumprod(DeltaRRR)
        wRRR = rng.standard_normal((hM.nc_rrr, hM.nc_orrr)) / np.sqrt(PsiRRR * tau[:, None])
        out["PsiRRR"], out["DeltaRRR"], out["wRRR"] = PsiRRR, DeltaRRR, wRRR
    else:
        out["PsiRRR"] = out["DeltaRRR"] = out["wRRR"] = None

    sigma = init_par.get("sigma")
    if sigma is None:
        est = hM.distr[:, 1] == 1
        sigma = np.array([FIXED_SIGMA2[int(f)] for f in hM.distr[:, 0]], dtype=float)
        # reference draws initial sigma (not 1/sigma) from Gamma(aSigma, bSigma)
        # (computeInitialParameters.R:115-118); replicated as-is
        sigma[est] = rng.gamma(hM.aSigma[est], 1.0 / hM.bSigma[est])
    out["sigma"] = np.asarray(sigma, dtype=float)

    # per-level factor blocks, padded to the static nf_max
    levels = []
    for r in range(nr):
        rL = hM.ranLevels[r]
        nf_max = int(nf_max_static[r])
        ncr = max(rL.x_dim, 1)
        np_r = hM.np_[r]
        nf0 = min(int(rL.nf_min), nf_max)
        for key_ in ("Delta", "Psi", "Lambda", "Eta"):
            if init_par.get(key_) is not None:
                arr = init_par[key_][r]
                nf0 = arr.shape[1] if key_ == "Eta" else arr.shape[0]
        mask = np.zeros(nf_max)
        mask[:nf0] = 1.0

        Delta = np.ones((nf_max, ncr))
        Delta[0, :] = rng.gamma(rL.a1, 1 / rL.b1)
        if nf0 > 1:
            Delta[1:nf0, :] = rng.gamma(np.broadcast_to(rL.a2, (nf0 - 1, ncr)),
                                        1 / np.broadcast_to(rL.b2, (nf0 - 1, ncr)))
        Psi = rng.gamma(rL.nu / 2, 2 / rL.nu, (nf_max, ns, ncr))
        tau = np.cumprod(Delta, axis=0)
        Lambda = rng.standard_normal((nf_max, ns, ncr)) / np.sqrt(Psi * tau[:, None, :])
        Lambda *= mask[:, None, None]
        Eta = rng.standard_normal((np_r, nf_max))
        alpha_idx = np.zeros(nf_max, dtype=np.int32)

        if init_par.get("Delta") is not None:
            Delta[:nf0] = np.asarray(init_par["Delta"][r]).reshape(nf0, ncr)
        if init_par.get("Psi") is not None:
            Psi[:nf0] = np.asarray(init_par["Psi"][r]).reshape(nf0, ns, ncr)
        if init_par.get("Lambda") is not None:
            Lambda[:nf0] = np.asarray(init_par["Lambda"][r]).reshape(nf0, ns, ncr)
        if init_par.get("Eta") is not None:
            Eta[:, :nf0] = np.asarray(init_par["Eta"][r])
        if init_par.get("Alpha") is not None:
            alpha_idx[:nf0] = np.asarray(init_par["Alpha"][r])

        levels.append(dict(Eta=Eta, Lambda=Lambda, Psi=Psi, Delta=Delta,
                           alpha_idx=alpha_idx, nf_mask=mask))
    out["levels"] = levels

    if init_par.get("rho") is not None:
        out["rho_idx"] = int(np.argmin(np.abs(init_par["rho"] - hM.rhopw[:, 0])))
    else:
        out["rho_idx"] = 0
    return out


def _fixed_effects_beta(hM: Hmsc, rng) -> np.ndarray:
    """Per-species single-species estimates: OLS for normal, IRLS probit /
    log-Poisson GLMs otherwise (reference computeInitialParameters.R:52-79)."""
    from scipy.special import ndtr
    from scipy.stats import norm

    Beta = np.zeros((hM.nc, hM.ns))
    for j in range(hM.ns):
        Xj = hM.XScaled[j] if hM.x_is_list else hM.XScaled
        yj = hM.Y[:, j]
        obs = ~np.isnan(yj)
        Xo, yo = Xj[obs], yj[obs]
        fam = int(hM.distr[j, 0])
        if fam == 1:
            Beta[:, j] = np.linalg.lstsq(Xo, yo, rcond=None)[0]
            continue
        # IRLS
        beta = np.zeros(hM.nc)
        for _ in range(25):
            eta = np.clip(Xo @ beta, -8, 8)
            if fam == 2:
                mu = np.clip(ndtr(eta), 1e-6, 1 - 1e-6)
                dmu = norm.pdf(eta)
                var = mu * (1 - mu)
            else:
                mu = np.exp(np.clip(eta, -20, 20))
                dmu = mu
                var = mu
            w = dmu**2 / np.maximum(var, 1e-10)
            z = eta + (yo - mu) / np.maximum(dmu, 1e-10)
            WX = Xo * w[:, None]
            try:
                new = np.linalg.solve(Xo.T @ WX + 1e-8 * np.eye(hM.nc), WX.T @ z)
            except np.linalg.LinAlgError:
                break
            if np.max(np.abs(new - beta)) < 1e-8:
                beta = new
                break
            beta = new
        Beta[:, j] = beta
    return Beta
