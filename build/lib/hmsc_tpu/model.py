"""Model specification layer (reference ``R/Hmsc.R:109-634``,
``R/setPriors.Hmsc.R:20-104``).

``Hmsc(...)`` validates and assembles the model: the response matrix Y, the
environmental design matrix X (shared, or per-species), species traits Tr,
phylogenetic correlation C, the study design -> random levels mapping Pi, the
observation-model table ``distr``, X/Tr/Y scaling with stored back-transform
parameters, and the default priors.  Everything here is host-side numpy; the
result is a frozen spec that the JAX sampling engine consumes.
"""

from __future__ import annotations

import numpy as np

from .random_level import HmscRandomLevel
from .utils.formula import design_matrix

__all__ = ["Hmsc", "set_priors"]

_DISTR_CODES = {
    # family: 1=normal, 2=probit, 3=Poisson(log); second entry: dispersion estimated?
    "normal": (1, 1),
    "probit": (2, 0),
    "poisson": (3, 0),
    "lognormal poisson": (3, 1),
}
# fixed residual variance when dispersion is not estimated, per family
# (reference computeInitialParameters.R:119-124)
FIXED_SIGMA2 = {1: 1.0, 2: 1.0, 3: 1e-2}


class XSelect:
    """One spike-and-slab variable-selection group (reference
    ``R/updateBetaSel.R``): covariate columns ``cov_group`` (0-based indices
    into X) are switched on/off jointly for each species group, with prior
    inclusion probability ``q[g]`` for species group ``g``; ``sp_group`` maps
    each species to its group (0-based)."""

    def __init__(self, cov_group, sp_group, q):
        self.cov_group = np.atleast_1d(np.asarray(cov_group, dtype=int))
        self.sp_group = np.asarray(sp_group, dtype=int)
        self.q = np.atleast_1d(np.asarray(q, dtype=float))
        if self.sp_group.ndim != 1:
            raise ValueError("Hmsc.setData: spGroup for XSelect must be a vector with one entry per species")
        if self.sp_group.min(initial=0) < 0 or self.sp_group.max(initial=0) >= len(self.q):
            raise ValueError("Hmsc.setData: spGroup for XSelect must index into q")


class Hmsc:
    """Hierarchical model of species communities: full model specification.

    Mirrors the reference constructor's capability surface: Y (+NAs), X as a
    data frame + formula, a plain matrix, or a per-species list; XSelect
    variable-selection groups; reduced-rank covariates XRRR; traits; phylogeny
    (correlation matrix C); study design with random levels; per-species
    observation models; and X/Tr/Y scaling with recorded back-transforms.
    """

    def __init__(self, Y, x_formula="~.", x_data=None, X=None, x_scale=True,
                 x_select=None,
                 xrrr_data=None, xrrr_formula="~.-1", XRRR=None, nc_rrr=2,
                 xrrr_scale=True,
                 y_scale=False,
                 study_design=None, ran_levels=None, ran_levels_used=None,
                 tr_formula=None, tr_data=None, Tr=None, tr_scale=True,
                 C=None, phylo_tree=None,
                 distr="normal", truncate_number_of_factors=True):
        # ---- response ----------------------------------------------------
        if hasattr(Y, "values"):  # pandas
            self.sp_names = [str(c) for c in Y.columns]
            Y = np.asarray(Y.values, dtype=float)
        else:
            Y = np.asarray(Y, dtype=float)
            self.sp_names = None
        if Y.ndim != 2:
            raise ValueError("Hmsc.setData: Y argument must be a matrix of sampling units times species")
        self.Y = Y
        self.ny, self.ns = Y.shape
        if self.sp_names is None:
            width = max(1, int(np.ceil(np.log10(max(self.ns, 2)))))
            self.sp_names = [f"sp{j+1:0{width}d}" for j in range(self.ns)]

        # ---- fixed-effect covariates ------------------------------------
        if x_data is not None and X is not None:
            raise ValueError("Hmsc.setData: only single of XData and X arguments must be specified")
        self.x_formula = None
        self.x_data = None
        self.x_is_list = False
        if x_data is not None:
            if isinstance(x_data, (list, tuple)):
                if len(x_data) != self.ns:
                    raise ValueError("Hmsc.setData: the length of XData list argument must be equal to the number of species")
                mats, names = [], None
                for df in x_data:
                    if len(df) != self.ny:
                        raise ValueError("Hmsc.setData: for each element of XData list the number of rows must be equal to the number of sampling units")
                    if _has_na(df):
                        raise ValueError("Hmsc.setData: all elements of XData list must contain no NA values")
                    m, names = design_matrix(x_formula, df)
                    mats.append(m)
                self.X = np.stack(mats, axis=0)  # (ns, ny, nc)
                self.x_is_list = True
                self.cov_names = names
            else:
                if len(x_data) != self.ny:
                    raise ValueError("Hmsc.setData: the number of rows in XData must be equal to the number of sampling units")
                if _has_na(x_data):
                    raise ValueError("Hmsc.setData: XData must contain no NA values")
                self.X, self.cov_names = design_matrix(x_formula, x_data)
            self.x_data = x_data
            self.x_formula = x_formula
        elif X is not None:
            if isinstance(X, (list, tuple)):
                if len(X) != self.ns:
                    raise ValueError("Hmsc.setData: the length of X list argument must be equal to the number of species")
                for m in X:
                    m = np.asarray(m, dtype=float)
                    if m.shape[0] != self.ny:
                        raise ValueError("Hmsc.setData: for each element of X list the number of rows must be equal to the number of sampling units")
                    if np.isnan(m).any():
                        raise ValueError("Hmsc.setData: all elements of X list must contain no NA values")
                self.X = np.stack([np.asarray(m, dtype=float) for m in X], axis=0)
                self.x_is_list = True
                self.cov_names = None
            else:
                Xm = np.asarray(X, dtype=float)
                if Xm.shape[0] != self.ny:
                    raise ValueError("Hmsc.setData: the number of rows in X must be equal to the number of sampling units")
                if np.isnan(Xm).any():
                    raise ValueError("Hmsc.setData: X must contain no NA values")
                self.X = Xm
                self.cov_names = None
        else:
            self.X = np.empty((self.ny, 0))
            self.cov_names = []
        self.nc = self.X.shape[-1]
        if self.cov_names is None:
            width = max(1, int(np.ceil(np.log10(max(self.nc, 2)))))
            self.cov_names = [f"cov{k+1:0{width}d}" for k in range(self.nc)]

        # ---- X scaling (reference Hmsc.R:281-330) ------------------------
        x_stack = self.X.reshape(-1, self.nc) if self.x_is_list else self.X
        self.x_intercept_ind = _find_intercept(x_stack, self.cov_names, "X")
        self.x_scale_par, x_scaled_stack = _scale_columns(
            x_stack, x_scale, self.x_intercept_ind)
        self.XScaled = (x_scaled_stack.reshape(self.X.shape)
                        if self.x_is_list else x_scaled_stack)

        # ---- variable selection -----------------------------------------
        x_select = list(x_select) if x_select else []
        self.ncsel = len(x_select)
        self.x_select = x_select
        for sel in x_select:
            if sel.cov_group.max(initial=0) >= self.nc:
                raise ValueError("Hmsc.setData: covGroup for XSelect cannot have values greater than number of columns in X")
            if sel.sp_group.shape != (self.ns,):
                raise ValueError("Hmsc.setData: spGroup for XSelect must be a vector with one entry per species")

        # ---- reduced-rank regression covariates -------------------------
        self.nc_nrrr = self.nc
        self.XRRR = None
        self.nc_orrr = 0
        self.nc_rrr = 0
        if xrrr_data is not None:
            if len(xrrr_data) != self.ny:
                raise ValueError("Hmsc.setData: the number of rows in XRRRData must be equal to the number of sampling units")
            if _has_na(xrrr_data):
                raise ValueError("Hmsc.setData: XRRRData must contain no NA values")
            self.XRRR, self.xrrr_names = design_matrix(xrrr_formula, xrrr_data)
            self.nc_orrr = self.XRRR.shape[1]
            self.nc_rrr = int(nc_rrr)
        elif XRRR is not None:
            XRRR = np.asarray(XRRR, dtype=float)
            if XRRR.ndim != 2:
                raise ValueError("Hmsc.setData: XRRR must be a matrix")
            if XRRR.shape[0] != self.ny:
                raise ValueError("Hmsc.setData: the number of rows in XRRR must be equal to the number of sampling units")
            if np.isnan(XRRR).any():
                raise ValueError("Hmsc.setData: XRRR must contain no NA values")
            self.XRRR = XRRR
            self.nc_orrr = XRRR.shape[1]
            self.nc_rrr = int(nc_rrr)
        if self.nc_rrr > 0:
            self.cov_names = self.cov_names + [f"XRRR_{k+1}" for k in range(self.nc_rrr)]
            self.nc = self.nc_nrrr + self.nc_rrr
            if xrrr_scale is False:
                self.xrrr_scale_par = np.vstack([np.zeros(self.nc_orrr), np.ones(self.nc_orrr)])
                self.XRRRScaled = self.XRRR
            else:
                if x_scale is False:
                    raise ValueError("Hmsc.setData: XRRR can't be scaled if X is not scaled")
                self.xrrr_scale_par, self.XRRRScaled = _scale_columns(
                    self.XRRR, xrrr_scale, None,
                    center=self.x_intercept_ind is not None)
        else:
            self.xrrr_scale_par = None
            self.XRRRScaled = None

        # ---- traits ------------------------------------------------------
        if tr_data is not None and Tr is not None:
            raise ValueError("Hmsc.setData: at maximum one of TrData and Tr arguments can be specified")
        if tr_data is not None:
            if tr_formula is None:
                raise ValueError("Hmsc.setData: TrFormula argument must be specified if TrData is provided")
            if len(tr_data) != self.ns:
                raise ValueError("Hmsc.setData: the number of rows in TrData should be equal to number of columns in Y")
            if _has_na(tr_data):
                raise ValueError("Hmsc.setData: TrData parameter must not contain any NA values")
            self.Tr, self.tr_names = design_matrix(tr_formula, tr_data)
        elif Tr is not None:
            Tr = np.asarray(Tr, dtype=float)
            if Tr.ndim != 2:
                raise ValueError("Hmsc.setData: Tr must be a matrix")
            if Tr.shape[0] != self.ns:
                raise ValueError("Hmsc.setData: the number of rows in Tr should be equal to number of columns in Y")
            if np.isnan(Tr).any():
                raise ValueError("Hmsc.setData: Tr parameter must not contain any NA values")
            self.Tr = Tr
            self.tr_names = None
        else:
            self.Tr = np.ones((self.ns, 1))
            self.tr_names = ["(Intercept)"]
        self.nt = self.Tr.shape[1]
        if self.tr_names is None:
            width = max(1, int(np.ceil(np.log10(max(self.nt, 2)))))
            self.tr_names = [f"tr{k+1:0{width}d}" for k in range(self.nt)]

        self.tr_intercept_ind = _find_intercept(self.Tr, self.tr_names, "Tr") \
            if tr_scale is not False else None
        self.tr_scale_par, self.TrScaled = _scale_columns(
            self.Tr, tr_scale, self.tr_intercept_ind)

        # ---- phylogeny ---------------------------------------------------
        # either a correlation matrix C, or a tree converted to its Brownian
        # correlation like the reference's ape::vcv.phylo path
        # (R/Hmsc.R:501-509; trees arrive as Newick strings here)
        self.C = None
        self.phylo_tree = None
        if C is not None and phylo_tree is not None:
            raise ValueError("Hmsc.setData: at maximum one of phyloTree and C arguments can be specified")
        if phylo_tree is not None:
            from .utils.phylo import phylo_corr
            self.C, _ = phylo_corr(phylo_tree, self.sp_names)
            self.phylo_tree = phylo_tree
        if C is not None:
            C = np.asarray(C, dtype=float)
            if C.shape != (self.ns, self.ns):
                raise ValueError("Hmsc.setData: the size of square matrix C must be equal to number of species")
            self.C = C

        # ---- study design / random levels -------------------------------
        if study_design is None:
            self.Pi = np.empty((self.ny, 0), dtype=np.int32)
            self.np_ = np.empty(0, dtype=int)
            self.nr = 0
            self.rl_names = []
            self.ranLevels = []
            self.df_pi = None
            self.pi_names = []
            if ran_levels:
                raise ValueError("Hmsc.setData: studyDesign is empty, but ranLevels is not")
        else:
            if len(study_design) != self.ny:
                raise ValueError("Hmsc.setData: the number of rows in studyDesign must be equal to number of rows in Y")
            ran_levels = dict(ran_levels or {})
            if ran_levels_used is None:
                ran_levels_used = list(ran_levels.keys())
            if any(n not in ran_levels for n in ran_levels_used):
                raise ValueError("Hmsc.setData: ranLevels must contain named elements corresponding to all levels listed in ranLevelsUsed")
            sd_cols = ([str(c) for c in study_design.columns]
                       if hasattr(study_design, "columns") else None)
            if sd_cols is not None and any(n not in sd_cols for n in ran_levels_used):
                raise ValueError("Hmsc.setData: studyDesign must contain named columns corresponding to all levels listed in ranLevelsUsed")
            self.study_design = study_design
            self.rl_names = list(ran_levels_used)
            self.ranLevels = [ran_levels[n] for n in self.rl_names]
            # Pi: per-level integer unit index per row; unit order = sorted
            # unique labels (R factor level order, Hmsc.R:547-551)
            self.Pi = np.empty((self.ny, len(self.rl_names)), dtype=np.int32)
            self.pi_names = []
            self.df_pi = []
            for r, name in enumerate(self.rl_names):
                col = (study_design[name] if sd_cols is not None
                       else np.asarray(study_design)[:, r])
                labels = [str(v) for v in np.asarray(col)]
                uniq = sorted(set(labels))
                lut = {u: i for i, u in enumerate(uniq)}
                self.Pi[:, r] = np.array([lut[v] for v in labels], dtype=np.int32)
                self.pi_names.append(uniq)
                self.df_pi.append(labels)
            self.np_ = np.array([len(u) for u in self.pi_names], dtype=int)
            self.nr = len(self.rl_names)
            if truncate_number_of_factors:
                for rL in self.ranLevels:
                    rL.nf_max = min(rL.nf_max, self.ns)
                    rL.nf_min = min(rL.nf_min, rL.nf_max)

        # ---- observation models -----------------------------------------
        self.distr = _encode_distr(distr, self.ns)

        # ---- Y scaling (normal species only; reference Hmsc.R:614-629) --
        if y_scale is False:
            self.y_scale_par = np.vstack([np.zeros(self.ns), np.ones(self.ns)])
            self.YScaled = self.Y
        else:
            y_scale_par = np.vstack([np.zeros(self.ns), np.ones(self.ns)])
            YScaled = self.Y.copy()
            ind = self.distr[:, 0] == 1
            if ind.any():
                mu = np.nanmean(self.Y[:, ind], axis=0)
                sd = np.nanstd(self.Y[:, ind], axis=0, ddof=1)
                y_scale_par[0, ind] = mu
                y_scale_par[1, ind] = sd
                YScaled[:, ind] = (self.Y[:, ind] - mu) / sd
            self.y_scale_par = y_scale_par
            self.YScaled = YScaled

        # ---- priors ------------------------------------------------------
        self.V0 = None
        self.f0 = None
        self.mGamma = None
        self.UGamma = None
        self.aSigma = None
        self.bSigma = None
        self.rhopw = None
        self.nuRRR = self.a1RRR = self.b1RRR = self.a2RRR = self.b2RRR = None
        set_priors(self, set_default=True)

        # posterior fields populated by sample_mcmc
        self.postList = None
        self.samples = None
        self.transient = None
        self.thin = None
        self.adaptNf = None

    # aliases matching the reference's field names
    @property
    def np(self):
        return self.np_

    def __repr__(self):
        return (f"Hmsc(ny={self.ny}, ns={self.ns}, nc={self.nc}, nt={self.nt}, "
                f"nr={self.nr}, phylo={self.C is not None})")


def set_priors(hM: Hmsc, V0=None, f0=None, mGamma=None, UGamma=None,
               aSigma=None, bSigma=None, nuRRR=None, a1RRR=None, b1RRR=None,
               a2RRR=None, b2RRR=None, rhopw=None, set_default: bool = False) -> Hmsc:
    """Default priors (reference ``setPriors.Hmsc.R:20-104``): Wishart on iV
    (V0=I, f0=nc+1), Gaussian on Gamma (0, I), gamma on iSigma (1, 5), and the
    101-point rho grid with P(rho=0)=0.5."""
    if V0 is not None:
        V0 = np.asarray(V0, dtype=float)
        if V0.shape != (hM.nc, hM.nc) or not np.allclose(V0, V0.T):
            raise ValueError("HMSC.setPriors: V0 must be a positive definite matrix of size equal to number of covariates nc")
        hM.V0 = V0
    elif set_default:
        hM.V0 = np.eye(hM.nc)
    if f0 is not None:
        if f0 < hM.nc:
            raise ValueError("HMSC.setPriors: f0 must be greater than number of covariates in the model nc")
        hM.f0 = float(f0)
    elif set_default:
        hM.f0 = float(hM.nc + 1)
    if mGamma is not None:
        mGamma = np.asarray(mGamma, dtype=float).ravel()
        if mGamma.size != hM.nc * hM.nt:
            raise ValueError("HMSC.setPriors: mGamma must be a vector of length equal to number of covariates times traits: nc x nt")
        hM.mGamma = mGamma
    elif set_default:
        hM.mGamma = np.zeros(hM.nc * hM.nt)
    if UGamma is not None:
        UGamma = np.asarray(UGamma, dtype=float)
        if UGamma.shape != (hM.nc * hM.nt,) * 2 or not np.allclose(UGamma, UGamma.T):
            raise ValueError("HMSC.setPriors: UGamma must be a positive definite matrix of size equal to nc x nt")
        hM.UGamma = UGamma
    elif set_default:
        hM.UGamma = np.eye(hM.nc * hM.nt)
    if aSigma is not None:
        hM.aSigma = np.broadcast_to(np.asarray(aSigma, dtype=float), (hM.ns,)).copy()
    elif set_default:
        hM.aSigma = np.ones(hM.ns)
    if bSigma is not None:
        hM.bSigma = np.broadcast_to(np.asarray(bSigma, dtype=float), (hM.ns,)).copy()
    elif set_default:
        hM.bSigma = np.full(hM.ns, 5.0)
    if rhopw is not None:
        if hM.C is None:
            raise ValueError("HMSC.setPriors: prior for phylogeny given, but no phylogenic relationship matrix was specified")
        rhopw = np.asarray(rhopw, dtype=float)
        if rhopw.ndim != 2 or rhopw.shape[1] != 2:
            raise ValueError("HMSC.setPriors: rhopw must be a matrix with two columns")
        hM.rhopw = rhopw
    elif set_default:
        rho_n = 100
        grid = np.arange(rho_n + 1) / rho_n
        w = np.concatenate([[0.5], np.full(rho_n, 0.5 / rho_n)])
        hM.rhopw = np.column_stack([grid, w])
    for name, val, dflt in (("nuRRR", nuRRR, 3.0), ("a1RRR", a1RRR, 1.0),
                            ("b1RRR", b1RRR, 1.0), ("a2RRR", a2RRR, 50.0),
                            ("b2RRR", b2RRR, 1.0)):
        if val is not None:
            setattr(hM, name, float(val))
        elif set_default:
            setattr(hM, name, dflt)
    return hM


# ---------------------------------------------------------------------------

def _has_na(df) -> bool:
    if hasattr(df, "isna"):
        return bool(df.isna().to_numpy().any())
    arr = np.asarray(df)
    return arr.dtype.kind == "f" and bool(np.isnan(arr).any())


def _find_intercept(M: np.ndarray, names, what: str):
    idx = [i for i, n in enumerate(names or []) if n in ("Intercept", "(Intercept)")]
    if len(idx) > 1:
        raise ValueError(f"Hmsc.setData: only one column of {what} matrix could be named Intercept or (Intercept)")
    if len(idx) == 1:
        if not np.all(M[:, idx[0]] == 1):
            raise ValueError(f"Hmsc.setData: intercept column in {what} matrix must be a column of ones")
        return idx[0]
    return None


def _scale_columns(M: np.ndarray, scale_arg, intercept_ind, center=None):
    """Center+scale non-binary columns; intercept-aware (reference
    ``Hmsc.R:281-330``).  Returns (scale_par (2,k), scaled copy)."""
    k = M.shape[1]
    scale_par = np.vstack([np.zeros(k), np.ones(k)])
    if scale_arg is False:
        return scale_par, M
    if scale_arg is True:
        scale_ind = np.array([not np.all(np.isin(M[:, j], (0.0, 1.0))) for j in range(k)])
    else:
        scale_ind = np.asarray(scale_arg, dtype=bool)
    if intercept_ind is not None:
        scale_ind = scale_ind.copy()
        scale_ind[intercept_ind] = False
    do_center = intercept_ind is not None if center is None else center
    out = M.astype(float).copy()
    for j in np.where(scale_ind)[0]:
        col = M[:, j]
        mu = col.mean() if do_center else 0.0
        sd = col.std(ddof=1) if do_center else np.sqrt(np.sum(col**2) / (len(col) - 1))
        scale_par[0, j] = mu
        scale_par[1, j] = sd
        out[:, j] = (col - mu) / sd
    return scale_par, out


def _encode_distr(distr, ns: int) -> np.ndarray:
    """Observation-model table: (ns, 2) [family, dispersion-estimated]
    (reference ``Hmsc.R:560-612``; the reference's dead columns 3-4 dropped)."""
    if isinstance(distr, str):
        distr = [distr] * ns
    distr_arr = np.asarray(distr)
    if distr_arr.dtype.kind in "OUS":
        out = np.zeros((ns, 2), dtype=np.int32)
        for j, name in enumerate(distr_arr.ravel()):
            if str(name) not in _DISTR_CODES:
                raise ValueError("Hmsc.setData: some of the distributions ill defined")
            out[j] = _DISTR_CODES[str(name)]
        return out
    distr_arr = np.asarray(distr_arr, dtype=np.int32)
    if distr_arr.ndim != 2 or distr_arr.shape[0] != ns:
        raise ValueError("Hmsc.setData: some of the distributions ill defined")
    out = distr_arr[:, :2].copy()
    if np.any((out[:, 0] < 1) | (out[:, 0] > 3)):
        raise ValueError("Hmsc.setData: some of the distributions ill defined")
    return out
